"""LLMEngine — continuous-batching serving core.

Execution model: one daemon thread owns the device (scheduler + ModelRunner)
and spins the step loop; the asyncio side (HTTP handlers) submits sequences
through a thread-safe inbox and receives ``RequestOutput`` items on per-request
asyncio queues. This is the TPU-native equivalent of the vLLM engine process
the reference stack treats as a black box (SURVEY.md §1 L4 contract).
"""

from __future__ import annotations

import asyncio
import os
import dataclasses
import queue as queue_mod
import threading
import time
from typing import AsyncIterator, Optional

import numpy as np

from production_stack_tpu import tracing
from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.kv_manager import KVPageManager
from production_stack_tpu.engine.model_loader import load_model
from production_stack_tpu.engine.runner import ModelRunner, StepInput
from production_stack_tpu.engine.lora import LoRAManager
from production_stack_tpu.engine.scheduler import SamplingParams, ScheduledBatch, Scheduler, Sequence
from production_stack_tpu.engine.tokenizer import load_tokenizer
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


@dataclasses.dataclass
class RequestOutput:
    seq_id: str
    text_delta: str
    token_ids: list[int]
    finished: bool
    finish_reason: Optional[str] = None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    cached_tokens: int = 0
    # per-token logprob entries aligned with token_ids (when requested):
    # {"logprob": float, "top_ids": [int], "top_logprobs": [float]}
    logprobs: Optional[list] = None


class LLMEngine:
    def __init__(self, cfg: EngineConfig, mesh=None):
        from production_stack_tpu.utils.compile_cache import enable_persistent_cache

        scope = None
        if cfg.distributed_num_processes > 1:
            import jax as _jax

            # jax.distributed is already initialized by serve(); executables
            # cached under a different process topology must not be reused
            scope = (
                f"mh{cfg.distributed_num_processes}p{_jax.process_index()}"
            )
        enable_persistent_cache(cfg.compilation_cache_dir, scope=scope)
        self.cfg = cfg
        model_mod, model_cfg, params = load_model(
            cfg.model, seed=cfg.seed, max_model_len=cfg.max_model_len
        )
        if cfg.attn_impl != "auto":
            model_cfg = dataclasses.replace(model_cfg, attn_impl=cfg.attn_impl)
        if getattr(model_cfg, "kv_write_mode", "pre") != cfg.kv_write_mode:
            if any(
                f.name == "kv_write_mode" for f in dataclasses.fields(model_cfg)
            ):
                model_cfg = dataclasses.replace(
                    model_cfg, kv_write_mode=cfg.kv_write_mode
                )
            else:
                logger.warning(
                    "kv_write_mode=%s unsupported for this model family; "
                    "keeping 'pre'", cfg.kv_write_mode,
                )
        # decode/prefill-kernel pipeline tuning rides the model config the
        # same way attn_impl does (the kernel call sites live in the model
        # forwards)
        for knob in (
            "decode_pages_per_block", "decode_prefetch_pages",
            "prefill_pages_per_block", "prefill_prefetch_pages",
        ):
            val = getattr(cfg, knob, 0)
            if val and any(
                f.name == knob for f in dataclasses.fields(model_cfg)
            ):
                model_cfg = dataclasses.replace(model_cfg, **{knob: val})
        # fused paged-KV write is a bool (default on): copy it whenever the
        # model family has the field and the value differs
        if any(
            f.name == "prefill_fused_kv_write"
            for f in dataclasses.fields(model_cfg)
        ) and model_cfg.prefill_fused_kv_write != cfg.prefill_fused_kv_write:
            model_cfg = dataclasses.replace(
                model_cfg, prefill_fused_kv_write=cfg.prefill_fused_kv_write
            )
        # KV cache dtype rides the model config like attn_impl (the
        # quantized read/write sites live in the model forwards); int8 is
        # gated on the combinations the quant contract covers
        self.kv_quant = cfg.kv_cache_dtype == "int8"
        if cfg.kv_cache_dtype != "auto":
            if not any(
                f.name == "kv_cache_dtype" for f in dataclasses.fields(model_cfg)
            ):
                raise ValueError(
                    f"kv_cache_dtype={cfg.kv_cache_dtype} is not supported "
                    "for this model family"
                )
            model_cfg = dataclasses.replace(
                model_cfg, kv_cache_dtype=cfg.kv_cache_dtype
            )
        if self.kv_quant:
            if cfg.kv_write_mode != "post":
                raise ValueError(
                    "--kv-cache-dtype int8 requires --kv-write-mode post"
                )
            if cfg.speculative_k:
                raise ValueError(
                    "--kv-cache-dtype int8 is not compatible with "
                    "--speculative-k (the spec scan carries raw pool blocks)"
                )
            if cfg.sequence_parallel_size > 1 or cfg.pipeline_parallel_size > 1:
                raise ValueError(
                    "--kv-cache-dtype int8 does not compose with sp/pp meshes"
                )
            if (cfg.kv_role != "none" or cfg.kv_transfer_device) and not cfg.kv_fabric:
                # gate lifted by the KV fabric (docs/kv-fabric.md): fabric
                # frames are (pages, scales) pairs, so quantized pages ship
                # with their exact scales. Without the fabric, the transfer
                # paths still move raw pool bytes — keep the PR 14 gate.
                raise ValueError(
                    "--kv-cache-dtype int8 with disaggregated-prefill or "
                    "device KV transfer requires --kv-fabric (fabric frames "
                    "carry the per-page scales; the raw page paths would "
                    "ship quantized bytes without them)"
                )
        self.model_cfg = model_cfg
        self.tokenizer = load_tokenizer(
            cfg.tokenizer or (cfg.model if "/" in cfg.model or cfg.model.startswith(".") else None)
        )
        kv_itemsize = (
            1 if self.kv_quant
            else np.dtype(getattr(model_cfg, "dtype", None) or "bfloat16").itemsize
        )
        page_bytes = (
            2 * model_cfg.num_layers * cfg.page_size * model_cfg.num_kv_heads
            * model_cfg.head_dim  # k+v
            * kv_itemsize
        )
        if self.kv_quant:
            # per-page scale rows ride the pool budget too (f32 per kv head,
            # k and v) — a rounding detail next to the 2x page shrink that
            # DOUBLES how many tokens the same kv_cache_memory_gb holds
            page_bytes += 2 * model_cfg.num_layers * model_cfg.num_kv_heads * 4
        # device telemetry (engine/devicemon.py): page footprint for the KV
        # pool-vs-headroom gauges, and the jax.monitoring compile listener
        # feeding vllm:compile_seconds_total + flight-recorder compile events
        self.kv_page_bytes = page_bytes
        from production_stack_tpu.engine import devicemon

        devicemon.install_compile_listener()
        # engine flight recorder (tracing/flightrecorder.py): bounded ring of
        # scheduler/KV/shed/step/compile events, auto-dumped on anomalies
        self._fr = tracing.configure_flightrecorder(
            capacity=cfg.flight_recorder_capacity,
            enabled=cfg.flight_recorder,
            dump_dir=(
                cfg.flight_recorder_dump_dir
                or os.environ.get("PSTPU_FLIGHTRECORDER_DIR")
            ),
        )
        num_pages = cfg.num_pages or max(64, int(cfg.kv_cache_memory_gb * 1e9 / page_bytes))
        from production_stack_tpu.parallel.mesh import make_mesh

        if mesh is None:
            mesh = make_mesh(
                tp=cfg.tensor_parallel_size,
                dp=cfg.data_parallel_size,
                sp=cfg.sequence_parallel_size,
                ep=cfg.expert_parallel_size,
                pp=cfg.pipeline_parallel_size,
            )
        # validate against the ACTUAL mesh so callers passing their own mesh
        # hit the same guards as config-built ones
        mesh_pp = dict(mesh.shape).get("pp", 1)
        mesh_dp = dict(mesh.shape).get("dp", 1)
        if mesh_pp > 1 and cfg.kv_write_mode != "post":
            raise ValueError(
                "--pipeline-parallel-size > 1 requires --kv-write-mode post"
            )
        if mesh_pp > 1 and mesh_dp > 1:
            raise ValueError(
                "pipeline parallelism does not compose with in-engine data "
                "parallelism yet; use router-level replicas for DP"
            )
        lora_targets = ()
        if cfg.enable_lora:
            from production_stack_tpu.engine.lora import _HF_TO_LEAF

            mods = [m.strip() for m in cfg.lora_target_modules.split(",") if m.strip()]
            bad = [m for m in mods if m not in _HF_TO_LEAF]
            if bad:
                raise ValueError(
                    f"unknown --lora-target-modules {bad}; valid: {sorted(_HF_TO_LEAF)}"
                )
            lora_targets = tuple(_HF_TO_LEAF[m] for m in mods)
        self.runner = ModelRunner(
            model_cfg, mesh=mesh, params=params, module=model_mod,
            num_pages=num_pages, page_size=cfg.page_size, seed=cfg.seed,
            enable_lora=cfg.enable_lora, max_loras=cfg.max_loras,
            max_lora_rank=cfg.max_lora_rank, lora_targets=lora_targets,
        )
        # KV quantization observability: bytes one token costs the pool
        # (the byte-wall number), and a startup quantize->dequantize
        # round-trip error bound on synthetic normal data — a cheap on-box
        # sanity check that the quant math is sane on this build, exported
        # as vllm:kv_quant_dequant_err_max
        from production_stack_tpu.ops.quant import kv_bytes_per_token

        self.kv_bytes_per_token = kv_bytes_per_token(
            model_cfg.num_layers, model_cfg.num_kv_heads, model_cfg.head_dim,
            cfg.page_size, self.kv_quant,
            np.dtype(getattr(model_cfg, "dtype", None) or "bfloat16").itemsize,
        )
        self.kv_quant_dequant_err_max = 0.0
        if self.kv_quant:
            from production_stack_tpu.ops.quant import (
                dequantize_page_host,
                quantize_page_host,
            )

            rng_chk = np.random.RandomState(0)
            x = rng_chk.randn(
                model_cfg.num_layers, cfg.page_size, model_cfg.num_kv_heads,
                model_cfg.head_dim,
            ).astype(np.float32)
            qx, sx = quantize_page_host(x)
            self.kv_quant_dequant_err_max = float(
                np.abs(dequantize_page_host(qx, sx) - x).max()
                / max(np.abs(x).max(), 1e-9)
            )
        # serving mesh degrees, read from the ACTUAL mesh (a caller-passed
        # mesh wins over the config): /stats + vllm:tensor_parallel_degree +
        # the flight recorder's sched events all report these, and the paged
        # pool's per-chip footprint is kv_page_bytes / tp per shard
        # (docs/multichip-serving.md)
        mesh_shape = dict(mesh.shape)
        self.tensor_parallel = mesh_shape.get("tp", 1)
        self.mesh_devices = int(mesh.devices.size)
        self.lora: Optional[LoRAManager] = None
        if cfg.enable_lora:
            self.lora = LoRAManager(
                self.runner, max_loras=cfg.max_loras, max_rank=cfg.max_lora_rank
            )
        self._offload = self._make_offload_connector(cfg)
        # offload I/O budget: explicit >= 0 is honored verbatim; the -1
        # default auto-derives from a startup link-bandwidth probe (0 on
        # PCIe-class links) — both the measurement and the chosen cap are
        # exported on /metrics. No offload configured -> nothing to cap.
        self.kv_link_bandwidth_bytes_per_s: Optional[float] = None
        self._max_io_pages = cfg.kv_offload_max_io_pages
        if self._max_io_pages < 0:
            if self._offload is not None:
                from production_stack_tpu.engine.linkprobe import (
                    derive_max_io_pages,
                    probe_link_bandwidth,
                )

                bw = probe_link_bandwidth()
                self.kv_link_bandwidth_bytes_per_s = bw
                self._max_io_pages = derive_max_io_pages(bw, page_bytes)
                logger.info(
                    "kv offload link probe: %s MB/s -> max_io_pages=%d",
                    "?" if bw is None else f"{bw / 1e6:.1f}",
                    self._max_io_pages,
                )
            else:
                self._max_io_pages = 0
        self.kv = KVPageManager(
            num_pages, cfg.page_size, offload=self._offload,
            max_io_pages=self._max_io_pages,
            spill_watermark=cfg.kv_spill_watermark,
        )
        # warm-start manifests (kvoffload/warmstart.py): restore the previous
        # incarnation's hot working set into the pool BEFORE the API server
        # exists, so the first post-restart requests hit warm prefixes. The
        # restore runs here on the construction thread — the engine loop has
        # not started, so the batched set_pages uploads race nothing.
        self.warm = None
        if cfg.warm_start:
            if self._offload is None:
                logger.warning(
                    "--warm-start needs an offload tier that survives "
                    "restarts (--kv-offload-dir or --kv-remote-url); disabled"
                )
            elif cfg.distributed_num_processes > 1:
                # the restore dispatches device programs during __init__,
                # before serve() wraps the runner in the multi-host
                # broadcaster — followers would never see them and desync
                logger.warning(
                    "--warm-start is single-host only for now; disabled"
                )
            else:
                from production_stack_tpu.kvoffload.warmstart import (
                    WarmStartManager,
                )

                self.warm = WarmStartManager(
                    self.kv, self._offload,
                    namespace=(
                        cfg.warm_start_namespace or cfg.kv_instance_id
                        or f"{cfg.name}-{cfg.port}"
                    ),
                    interval_s=cfg.warm_start_interval_s,
                    max_pages=cfg.warm_start_max_pages,
                    model=cfg.name,
                )
                self.warm.restore()
        # fleet-wide KV directory (ISSUE 9, docs/kv-directory.md): publisher
        # advertises this engine's prefix-cache claims (dirty-batched,
        # off-thread); puller prefetches fleet-warm prefixes at admission.
        # Created AFTER warm restore so the generation fence tracks the
        # warm-start generation (boot epoch without --warm-start: wall-clock
        # seconds are monotonic across restarts, which is all fencing needs).
        self._kvdir_pub = None
        self._kvdir_pull = None
        if cfg.kv_directory_url:
            from production_stack_tpu.kvdirectory import (
                DirectoryPublisher,
                DirectoryPuller,
            )

            self._kvdir_pub = DirectoryPublisher(
                cfg.kv_directory_url,
                engine_url=self._advertised_url(cfg),
                page_size=cfg.page_size,
                generation=(
                    self.warm.generation if self.warm is not None
                    else int(time.time())
                ),
                flush_interval_s=cfg.kv_directory_flush_s,
                # shared-tier claims need the write-through remote tier;
                # without one this engine's blobs are private (publish-only
                # resident claims still feed router-v2 resident ranking)
                shared_enabled=(
                    self._offload is not None
                    and self._offload.store.remote is not None
                ),
            )
            self.kv.directory = self._kvdir_pub
            if self.kv.hash_to_page:
                # warm restore ran before the publisher existed: re-advertise
                # the restored working set under the NEW generation (this is
                # also what makes a reborn engine republish after a restart)
                self._kvdir_pub.publish_resident([
                    (h, self.kv.pages[pid].depth, self.kv.pages[pid].hits)
                    for h, pid in self.kv.hash_to_page.items()
                ])
            if (
                cfg.kv_directory_pull
                and self._offload is not None
                and self._offload.store.remote is not None
            ):
                # same gate as shared_enabled: the shared tier IS the remote
                # cache server — without one every prefetch would miss while
                # still paying a directory round trip per admission
                self._kvdir_pull = DirectoryPuller(
                    cfg.kv_directory_url, self.kv, self._offload.store,
                    cfg.page_size,
                    max_pages=cfg.kv_directory_pull_max_pages,
                )
            elif cfg.kv_directory_pull:
                logger.warning(
                    "--kv-directory-pull needs --kv-remote-url (the shared "
                    "tier blobs are pulled from the cache server); "
                    "publish-only mode"
                )
        # scale-up warm-up (docs/migration.md): pull the fleet's top warm
        # chunks into the LOCAL tiers before the API server exists (still on
        # the construction thread, like warm restore — blocking here is what
        # makes "warm before /ready" true). Blobs land tier-side only; the
        # first matching request's admission restores them into HBM through
        # the ordinary _extend_from_offload path and scores a prefix hit.
        self.kv_directory_prefetched_pages = 0
        if (
            cfg.warm_prefetch_on_boot > 0
            and cfg.kv_directory_url
            and self._offload is not None
        ):
            self.kv_directory_prefetched_pages = self._boot_prefetch(cfg)
        # disaggregated prefill (SURVEY.md §2.3): producer pushes finished
        # prefill KV to the decode peer; consumer receives into its store
        self._kv_sender = None
        self._kv_receiver = None
        if cfg.kv_role == "producer":
            if not cfg.kv_peer_url:
                raise ValueError("kv_role=producer requires --kv-peer-url")
            from production_stack_tpu.kvoffload.transfer import KVTransferSender

            self._kv_sender = KVTransferSender(cfg.kv_peer_url)
            if cfg.kv_transfer_device and cfg.distributed_num_processes <= 1:
                # single-host producer: same assignment protocol as the
                # multi-host path with P=1 — one endpoint, direct offers
                # (multi-host arming happens in serve() after the
                # BroadcastingRunner wrap: enable_multihost_device_kv)
                try:
                    self.runner.kv_endpoint_host = cfg.kv_transfer_device_host
                    self.runner.kv_endpoint_start()
                    self._kv_sender.enable_multihost(
                        [self.runner.kv_endpoint.address],
                        lambda pid, base, pullers: self.runner.kv_offer_page(
                            pid, base, pullers
                        ),
                    )
                except Exception as e:  # noqa: BLE001 - platform w/o transfer svc
                    logger.warning(
                        "device kv transfer unavailable (%s); using TCP blobs",
                        e,
                    )
        elif cfg.kv_role == "consumer":
            from production_stack_tpu.kvoffload.transfer import (
                DeviceStaging,
                KVTransferReceiver,
            )

            endpoint = self._make_device_endpoint(cfg)
            staging = None
            if endpoint is not None:
                staging = DeviceStaging(cfg.kv_transfer_stage_mb << 20)
                self._offload.device_staging = staging
            self._kv_receiver = KVTransferReceiver(
                self._offload.store, host=cfg.host, port=cfg.kv_transfer_port,
                device_endpoint=endpoint, staging=staging,
            )
            self._kv_receiver.start()
        # peer-to-peer KV fabric (ISSUE 16, docs/kv-fabric.md): one
        # engine-to-engine transfer plane for streamed disagg prefill,
        # directory resident-page pulls, and migration page-chain ships.
        # The listener serves resident pages straight off the device pool
        # (gathers run on the device thread); pushed frames land as tier
        # blobs in the LOCAL store, where the ordinary admission/restore
        # path finds them. Every fabric consumer falls back to the tier
        # path on failure (client breaker + counted fallbacks).
        self._fabric_server = None
        self._fabric_client = None
        self._fabric_peer_addr: Optional[str] = None
        if cfg.kv_fabric:
            from production_stack_tpu.kvfabric import (
                FrameAssembler,
                KVFabricClient,
                KVFabricServer,
            )

            self._fabric_asm = FrameAssembler()
            self._fabric_client = KVFabricClient(retries=cfg.kv_fabric_retries)
            self._fabric_server = KVFabricServer(
                host=cfg.host,
                port=cfg.kv_fabric_port,
                generation=(
                    self._kvdir_pub.generation
                    if self._kvdir_pub is not None
                    else (
                        self.warm.generation if self.warm is not None
                        else int(time.time())
                    )
                ),
                quant=self.kv_quant,
                page_size=cfg.page_size,
                nlayers=model_cfg.num_layers,
                pages_fn=self._fabric_pages,
                sink_fn=self._fabric_sink,
                advertise_host=cfg.advertise_host or None,
            )
            self._fabric_server.start()
            if self._kvdir_pull is not None:
                # resident-page pulls go engine-to-engine: the puller gets
                # the fabric client plus this engine's advertised URL (so
                # it never "pulls" from itself) — tier fetch stays the
                # fallback inside the puller
                self._kvdir_pull.enable_fabric(
                    self._fabric_client,
                    self._advertised_url(cfg),
                    serde=self._offload.serde,
                )
        self.scheduler = Scheduler(
            self.kv,
            max_num_seqs=cfg.max_num_seqs,
            max_model_len=cfg.max_model_len,
            prefill_chunk=cfg.prefill_chunk if cfg.enable_chunked_prefill else 10**9,
            prefill_batch=cfg.prefill_batch,
            enable_prefix_caching=cfg.enable_prefix_caching,
            batch_multiple=cfg.data_parallel_size,
            decode_steps=cfg.decode_steps,
            decode_pipeline=cfg.decode_pipeline,
            spec_k=cfg.speculative_k,
            spec_ngram=cfg.speculative_ngram,
            max_waiting_seqs=cfg.max_waiting_seqs,
            queue_deadline_s=cfg.queue_deadline_s,
            interactive_reserve=cfg.interactive_reserve,
            batch_queue_deadline_s=cfg.batch_queue_deadline_s,
            batch_prefill_share=cfg.batch_prefill_share,
        )
        # this loop dispatches run-ahead prefills behind in-flight chains
        # (_runahead_prefills), which is what licenses the scheduler's
        # one-extra-burst chaining floor past the admission-wait budget
        self.scheduler.runahead_available = True
        # live sequence migration (production_stack_tpu/migration): frozen
        # sequences are OUT of the running set but keep their pages while
        # the target decides; device-thread-owned by construction (freeze/
        # commit/rollback/abort all run as device commands), so no lock
        self._frozen: dict[str, Sequence] = {}  # owned-by: device-thread
        self.migration = None
        if cfg.migration:
            from production_stack_tpu.migration import MigrationManager

            self.migration = MigrationManager(self)
        self._inbox: queue_mod.Queue = queue_mod.Queue()
        # prefill dispatches whose results were never fetched (skip-fetch
        # optimization); a deferred device error taints these sequences
        self._unfetched: list = []
        # two-writer maps (event-loop generate() registers/pops, device
        # thread _emit/_process_token reads/writes): every touch goes
        # through _lock — graftcheck GC004 enforces the discipline
        self._outputs: dict[str, tuple[asyncio.AbstractEventLoop, asyncio.Queue]] = {}  # guarded-by: _lock
        self._texts: dict[str, str] = {}  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._sleeping = False
        self._sleep_level = 0
        self._lock = threading.Lock()
        # serving stats (scraped by /metrics)
        self.total_prompt_tokens = 0
        self.total_generation_tokens = 0
        # dispatch-shape observability: chaining only engages on a quiescent
        # batch, and whether it does dominates decode throughput on
        # network-attached chips (each unchained dispatch pays a fetch RTT)
        self.decode_dispatches_total = 0
        self.decode_chained_dispatches_total = 0
        # prefill dispatches issued while a decode chain was in flight
        # (run-ahead): the device queued them behind the chain instead of
        # idling through its fetch + scheduling turnaround
        self.runahead_prefill_dispatches_total = 0
        self.spec_draft_tokens = 0     # drafts proposed (rounds * spec_k)
        self.spec_accepted_tokens = 0  # drafts the target accepted
        self.num_preemptions = 0
        # load-shed accounting (admission control). Single writer per
        # counter — a shared `dict[k] += 1` from two threads drops
        # increments (load/add/store is not atomic): requests_shed is
        # mutated ONLY on the engine device thread (_inbox_accept /
        # _shed_expired), api_requests_shed ONLY on the aiohttp event loop
        # (the API-layer fast-path 429); stats() sums them
        self.requests_shed = {"queue_full": 0, "queue_deadline": 0}
        self.api_requests_shed = 0
        # per-SLO-class shed accounting (docs/failure-handling.md priority
        # classes), same single-writer split: requests_shed_by_class is
        # mutated ONLY on the device thread, api_requests_shed_by_class ONLY
        # on the event loop (note_api_shed); stats() sums the pairs
        self.requests_shed_by_class = {"interactive": 0, "batch": 0}  # owned-by: device-thread
        self.api_requests_shed_by_class = {"interactive": 0, "batch": 0}  # owned-by: event-loop
        # admission instrumentation: arrival -> first prefill dispatch, in ms
        # (the piece of TTFT a chained decode dispatch can inflate — an
        # arrival mid-chain waits for the whole chain before its prefill).
        # /metrics exposes p50/p99 as the ttft_hop_admission_wait gauge.
        import collections

        self.admission_wait_ms: collections.deque = collections.deque(maxlen=2048)
        # recent arrival timestamps, feeding the adaptive chain-depth bound
        # (scheduler.arrival_rate): chaining pays off only on a quiescent
        # batch, so expected arrivals during a chain cap its depth
        self._arrival_times: collections.deque = collections.deque(maxlen=64)
        # per-burst wall-time EMA feeding the same bound; seeded at a
        # typical network-attached-chip burst cost until measured
        self._burst_seconds = 0.05
        # engine-loop section time accounting (seconds, cumulative), scraped
        # via /metrics: attributes serving-loop overhead between the device
        # program (step = stage+dispatch+fetch) and the host-side bookkeeping
        # (apply = scheduler state, emit = detokenize+queue put)
        self.loop_seconds = {
            "wait": 0.0, "schedule": 0.0, "step": 0.0, "apply": 0.0,
            "emit": 0.0, "chain_dispatch": 0.0, "chain_fetch": 0.0,
        }
        # per-request SLO accounting (ISSUE 7 tentpole b): every finished
        # sequence appends a terminal record (queue wait, TTFT, tokens,
        # inter-token p99, KV pages peak, outcome) to this bounded log; the
        # router scrapes GET /slo_records with a cursor and aggregates the
        # records into per-model/backend SLO attainment counters. Single
        # writer (this device thread); /slo_records snapshots with a retry.
        import itertools

        self.slo_records: collections.deque = collections.deque(maxlen=2048)
        self._slo_seq = itertools.count(1)
        # rolling window of recent interactive ok-request latencies, feeding
        # the interactive_{ttft,itl}_p99_ms gauges the fleet controller's
        # latency-protection policy scrapes (docs/failure-handling.md
        # priority classes); bounded deque appends are atomic, stats()
        # snapshots with list()
        self._interactive_ttft_ms: collections.deque = collections.deque(maxlen=64)  # owned-by: device-thread
        self._interactive_itl_ms: collections.deque = collections.deque(maxlen=64)  # owned-by: device-thread
        # engine step index: every dispatched batch increments it; flight
        # recorder events carry it so a debug window can be cut by step range
        self.step_idx = 0
        # shed-burst anomaly trigger (flight recorder): timestamps of recent
        # sheds across BOTH writer threads (deque.append is thread-safe)
        self._shed_times: collections.deque = collections.deque(maxlen=64)

    # -- admission control / load shedding ----------------------------------

    def saturated(self, priority: str = "interactive") -> bool:
        """Waiting queue at its configured bound for this SLO class — the
        API layer should shed new generation work with 429 + Retry-After
        instead of queueing it. Batch saturates ``interactive_reserve``
        slots early (scheduler.saturated)."""
        return self.scheduler.saturated(priority)

    def shed_retry_after(self) -> float:
        return max(0.0, self.cfg.shed_retry_after_s)

    def can_shed_queued(self) -> bool:
        """Whether already-accepted requests may still shed after submission
        (queue deadline, or the engine-side authoritative queue bound in
        _inbox_accept) — the API layer then defers response headers until
        the first engine output so a shed converts to a clean 429 instead of
        a committed 200."""
        return (
            self.scheduler.queue_deadline_s > 0
            or self.scheduler.max_waiting_seqs > 0
        )

    def _note_shed(self, reason: str, seq: "Optional[Sequence]" = None) -> None:
        """Flight-recorder shed event + burst detection: a burst of sheds is
        THE overload postmortem moment — dump the surrounding scheduler/KV
        window while it is still in the ring. Thread-safe (called from the
        device thread for engine sheds and the event loop for API-layer
        fast-path sheds)."""
        fr = self._fr
        now = time.monotonic()
        self._shed_times.append(now)
        if not fr.enabled:
            return
        tr = getattr(seq, "trace", None)
        fr.record(
            "shed", step=self.step_idx, reason=reason,
            seq_id=seq.seq_id if seq is not None else None,
            waiting=self.scheduler.num_waiting(),
            running=self.scheduler.num_running(),
            trace_id=getattr(tr, "trace_id", None),
        )
        burst = self.cfg.flight_recorder_shed_burst
        if burst > 0:
            recent = sum(1 for t in list(self._shed_times) if now - t <= 5.0)
            if recent >= burst:
                # async: sheds fire on the event loop (API fast path) and
                # the device thread — neither may pay the ring serialization
                fr.dump_async("shed_burst")

    def note_api_shed(
        self,
        request_id: Optional[str] = None,
        priority: str = "interactive",
    ) -> None:
        """API-layer fast-path shed (api_server owns that counter; the event,
        burst accounting, the per-class counter, AND the SLO terminal record
        land here so neither the recorder nor the router's availability
        counters are blind to the most common overload shed — no Sequence
        ever exists for these). Thread-safe: deque.append and the itertools
        cursor are atomic, and this is the only writer on the event loop."""
        if priority not in self.api_requests_shed_by_class:
            priority = "interactive"
        self.api_requests_shed_by_class[priority] += 1
        self._note_shed("api_queue_full")
        self.slo_records.append({
            "seq": next(self._slo_seq),
            "request_id": request_id or "unknown",
            "model": self.cfg.name,
            "outcome": "shed",
            "finish_reason": "shed",
            "priority": priority,
            "queue_ms": 0.0,
            "ttft_ms": None,
            "e2e_ms": None,
            "prompt_tokens": 0,
            "output_tokens": 0,
            "cached_tokens": 0,
            "itl_p99_ms": None,
            "kv_pages_peak": 0,
            "trace_id": None,
            "t": time.time(),
        })

    def _shed_expired(self) -> None:
        """Shed waiting requests past the queue deadline: finish with reason
        'shed' and emit the terminal output so the consumer (blocked on its
        output queue) converts it to a 429 instead of hanging. shed_exempt
        sequences (parallel-sampling siblings, see Sequence.shed_exempt) are
        skipped: their request is mid-stream — shedding one choice could
        never surface as a clean 429."""
        for s in self.scheduler.expired_waiting():
            if s.shed_exempt:
                continue
            self.scheduler._finish(s, "shed")
            self.requests_shed["queue_deadline"] += 1
            self.requests_shed_by_class[
                s.priority if s.priority in self.requests_shed_by_class
                else "interactive"
            ] += 1
            self._note_shed("queue_deadline", s)
            self._emit(s, "")

    def _recent_arrival_rate(self, window: float = 1.0) -> float:
        """Arrivals/sec over the trailing ``window`` seconds."""
        now = time.monotonic()
        n = 0
        for t in reversed(self._arrival_times):
            if now - t > window:
                break
            n += 1
        return n / window


    def _make_device_endpoint(self, cfg: EngineConfig):
        """Device-to-device KV endpoint (opt-in; falls back to None so the
        TCP blob path serves everything when the transfer service cannot
        start on this platform)."""
        if not cfg.kv_transfer_device:
            return None
        if cfg.distributed_num_processes > 1:
            # multi-host: endpoints are per-process and REPLICATED through
            # the step stream (runner.kv_endpoint_start); serve() arms them
            # via enable_multihost_device_kv after the broadcaster is wired
            return None
        from production_stack_tpu.kvoffload.transfer import DeviceKVEndpoint

        try:
            ep = DeviceKVEndpoint(self.runner, host=cfg.kv_transfer_device_host)
            logger.info("device kv endpoint at %s", ep.address)
            return ep
        except Exception as e:  # noqa: BLE001 - platform without transfer svc
            logger.warning(
                "device kv transfer unavailable (%s); using TCP blobs", e
            )
            return None

    def _make_offload_connector(self, cfg: EngineConfig):
        """Build the LMCache-equivalent offload connector when any tier or the
        KV-index controller is configured (SURVEY.md §7 step 5). A
        disaggregated-prefill consumer always gets a CPU tier — received KV
        lands there before admission restores it into HBM."""
        if cfg.kv_role == "consumer" and cfg.kv_offload_cpu_gb <= 0:
            cfg = dataclasses.replace(cfg, kv_offload_cpu_gb=2.0)
        if not (
            cfg.kv_offload_cpu_gb > 0
            or cfg.kv_offload_dir
            or cfg.kv_remote_url
            or cfg.kv_controller_url
            or cfg.kv_directory_url
        ):
            return None
        from production_stack_tpu.kvoffload.connector import KVOffloadConnector

        return KVOffloadConnector(
            self.runner,
            cpu_bytes=int(cfg.kv_offload_cpu_gb * 1e9),
            disk_path=cfg.kv_offload_dir,
            disk_bytes=int(cfg.kv_offload_disk_gb * 1e9) if cfg.kv_offload_dir else 0,
            remote_url=cfg.kv_remote_url,
            serde=cfg.kv_serde,
            controller_url=cfg.kv_controller_url,
            instance_id=cfg.kv_instance_id or f"{cfg.name}-{cfg.port}",
            engine_url=self._advertised_url(cfg),
        )

    def _boot_prefetch(self, cfg: EngineConfig) -> int:
        """Directory-driven scale-up prefetch: ask the cache server for the
        fleet's top warm chunks (``dir_top_prefixes``, heads-first) and pull
        their blobs into the LOCAL host tiers. Runs on the construction
        thread BEFORE the server reports ready. Never raises — a cold boot
        is a degradation, not a failure."""
        try:
            from production_stack_tpu.kvoffload.protocol import (
                BlockingClient,
                parse_hostport,
            )

            host, port = parse_hostport(cfg.kv_directory_url, default_port=8200)
            client = BlockingClient(host, port, timeout=10)
            try:
                hdr, _ = client.request({
                    "op": "dir_top_prefixes",
                    "limit": cfg.warm_prefetch_on_boot,
                    "page_size": cfg.page_size,
                })
            finally:
                client.close()
            keys = hdr.get("hashes") or []
            store = self._offload.store
            n = 0
            for key in keys:
                try:
                    if store.contains_local(key) or store.get(key) is not None:
                        n += 1
                except Exception:  # noqa: BLE001 - one bad blob: keep pulling
                    logger.exception("boot prefetch failed for %s", key)
            logger.info(
                "warm prefetch on boot: pulled %d/%d fleet-warm chunks into "
                "local tiers", n, len(keys),
            )
            return n
        except Exception as e:  # noqa: BLE001 - directory down = cold boot
            logger.warning("warm prefetch on boot failed: %s", e)
            return 0

    def _advertised_url(self, cfg: EngineConfig) -> str:
        """URL other pods (router, KV controller/directory consumers) reach
        this engine at. A wildcard bind address would never match a
        discovered endpoint, so it resolves to the pod hostname's address."""
        host = cfg.advertise_host or cfg.host
        if host in ("0.0.0.0", "::", ""):
            import socket

            try:
                host = socket.gethostbyname(socket.gethostname())
            except OSError:
                host = "127.0.0.1"
            if cfg.kv_controller_url or cfg.kv_directory_url:
                logger.warning(
                    "--advertise-host not set; registering with the KV "
                    "index as %s (set it to the pod IP for kvaware routing)",
                    host,
                )
        return f"http://{host}:{cfg.port}"

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run_loop, daemon=True, name="engine-loop")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._inbox.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        if self._kvdir_pub is not None:
            self._kvdir_pub.stop()
        if self._offload is not None:
            self._offload.stop()
        if self._kv_sender is not None:
            self._kv_sender.close()
        ep = getattr(self.runner, "kv_endpoint", None)
        if ep is not None:
            ep.close()
        if self._kv_receiver is not None:
            self._kv_receiver.stop()
            if self._kv_receiver.device_endpoint is not None:
                self._kv_receiver.device_endpoint.close()
            if self._kv_receiver.staging is not None:
                self._kv_receiver.staging.clear()
        if self._fabric_server is not None:
            self._fabric_server.stop()
        if self._fabric_client is not None:
            self._fabric_client.close()

    def _run_on_device_thread(self, fn, timeout: float = 120.0):
        """Run ``fn`` on the engine device thread (serialized with steps via
        the device_cmd inbox) and return its result. Replicated runner
        dispatches MUST go through here from any other thread, or the
        leader's local dispatch order could diverge from the broadcast
        order the followers replay.

        Re-entrant: called ON the device thread (e.g. a staging-TTL expiry
        firing inside a prefix-cache probe during scheduling) it runs ``fn``
        directly — queueing would deadlock waiting on ourselves."""
        if threading.current_thread() is self._thread:
            return fn()
        done = threading.Event()
        box: dict = {}

        def run():
            try:
                box["r"] = fn()
            except Exception as e:  # noqa: BLE001 - re-raised on the caller
                box["e"] = e
            finally:
                done.set()

        self._inbox.put(("device_cmd", run))
        if not done.wait(timeout):
            raise TimeoutError("device thread did not service the command")
        if "e" in box:
            raise box["e"]
        return box.get("r")

    def enable_multihost_device_kv(self) -> None:
        """Arm the multi-host device-to-device KV path (called by serve() on
        the leader AFTER the BroadcastingRunner wrap): every process starts a
        transfer endpoint (replicated kv_endpoint_start, addresses exchanged
        through the JAX coordination KV store), the producer's sender learns
        the per-process addresses, and the consumer's receiver gets the
        replicated pull/unstage dispatchers. KV pages then move
        device->device over DCN between the prefill and decode clusters —
        the reference's NIXL GPU-direct analogue
        (deployment-vllm-multi.yaml:256-296) — with TCP blobs as the
        per-page fallback."""
        self.runner.kv_endpoint_start()  # replicated -> all processes
        n = self.cfg.distributed_num_processes
        if self._kv_sender is not None:
            from jax._src import distributed as jdist

            client = jdist.global_state.client
            addrs = [
                client.blocking_key_value_get(f"pstpu/kv_ep/{i}", 300_000)
                for i in range(n)
            ]
            self._kv_sender.enable_multihost(
                addrs,
                lambda pid, base, pullers: self.runner.kv_offer_page(
                    pid, base, pullers
                ),
            )
        if self._kv_receiver is not None:
            from production_stack_tpu.kvoffload.transfer import DeviceStaging

            staging = DeviceStaging(
                self.cfg.kv_transfer_stage_mb << 20,
                on_expire=self._mh_unstage,
            )
            if self._offload is not None:
                self._offload.device_staging = staging
            self._kv_receiver.staging = staging
            self._kv_receiver.procs = n
            self._kv_receiver.pull_fn = self._mh_pull
            self._kv_receiver.unstage_fn = self._mh_unstage

    def _mh_pull(self, assignments, shape, dtype, key: str) -> int:
        return int(self._run_on_device_thread(
            lambda: self.runner.kv_pull_page(assignments, shape, dtype, key)
        ) or 0)

    def _mh_unstage(self, key: str) -> None:
        try:
            self._run_on_device_thread(
                lambda: self.runner.kv_unstage_page(key)
            )
        except Exception:  # noqa: BLE001 - cleanup is best-effort
            logger.exception("multi-host kv unstage(%s) failed", key)

    # -- request api (asyncio side) -----------------------------------------

    async def generate(
        self,
        seq_id: str,
        prompt: Optional[str] = None,
        prompt_token_ids: Optional[list[int]] = None,
        params: Optional[SamplingParams] = None,
        lora_name: Optional[str] = None,
        trace: Optional[object] = None,
        shed_exempt: bool = False,
        priority: str = "interactive",
    ) -> AsyncIterator[RequestOutput]:
        params = params or SamplingParams()
        if priority not in ("interactive", "batch"):
            priority = "interactive"  # closed label set, unknown -> default
        if lora_name and self.lora is None:
            raise ValueError("LoRA is not enabled (--enable-lora)")
        if prompt_token_ids is None:
            prompt_token_ids = self.tokenizer.encode(prompt or "")
        if not prompt_token_ids:
            prompt_token_ids = [self.tokenizer.bos_token_id]
        if len(prompt_token_ids) + 1 > self.cfg.max_model_len:
            raise ValueError(
                f"prompt has {len(prompt_token_ids)} tokens, max_model_len is "
                f"{self.cfg.max_model_len}"
            )
        if self._sleeping:
            raise RuntimeError("engine is sleeping")
        if self._kvdir_pull is not None and not lora_name:
            # fleet-warm pull (docs/kv-directory.md): prefetch directory-
            # reported restorable prefix blobs into the LOCAL host tiers
            # before the sequence reaches the scheduler, so the device-thread
            # restore reads locally instead of probing the remote per chunk.
            # Best-effort with its own timeout/backoff; LoRA prompts are
            # skipped (adapter-salted chains are never shared fleet-wide).
            try:
                await self._kvdir_pull.maybe_prefetch(prompt_token_ids)
            except Exception:  # noqa: BLE001 - pull is a hint, never a gate
                logger.exception("kv directory prefetch failed")
        lora_slot, cache_salt = 0, b""
        if lora_name:
            # atomic resolve+pin, LAST before enqueue: every later path runs
            # inside the try/finally, so the ref is always released
            lora_slot, cache_salt = self.lora.acquire(lora_name)
        out_q: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_running_loop()
        with self._lock:
            self._outputs[seq_id] = (loop, out_q)
            self._texts[seq_id] = ""
        seq = Sequence(
            seq_id=seq_id, prompt_ids=list(prompt_token_ids), params=params,
            lora_slot=lora_slot, cache_salt=cache_salt, trace=trace,
            shed_exempt=shed_exempt, priority=priority,
        )
        self._inbox.put(seq)
        try:
            while True:
                item = await out_q.get()
                yield item
                if item.finished:
                    break
        finally:
            with self._lock:
                self._outputs.pop(seq_id, None)
                self._texts.pop(seq_id, None)
            if lora_slot:
                self.lora.release(lora_slot)
            self._inbox.put(("abort", seq_id))

    def abort(self, seq_id: str) -> None:
        self._inbox.put(("abort", seq_id))

    # -- engine loop (device thread) ----------------------------------------

    def _drain_inbox(self, block: bool, defer_aborts: bool = False) -> list:
        """Drain queued arrivals/aborts/device commands. With
        ``defer_aborts`` (mid-chain run-ahead), aborts are RETURNED instead
        of applied: an abort frees the sequence's pages, and a page freed
        while a dispatched-but-unfetched chain still writes to it must not
        be reallocated to a run-ahead admission. The caller re-queues them
        once the chain has been applied (aborts are idempotent and
        order-independent — abort of an already-finished seq is a no-op)."""
        deferred: list = []
        timeout = 0.5 if block else None
        while True:
            try:
                item = self._inbox.get(block=block, timeout=timeout)
            except queue_mod.Empty:
                return deferred
            block = False
            if item is None:
                return deferred
            if isinstance(item, tuple) and item[0] == "device_cmd":
                item[1]()  # LoRA update / embed forward, serialized with steps
            elif isinstance(item, tuple) and item[0] == "abort":
                if defer_aborts:
                    deferred.append(item)
                    continue
                # a FROZEN sequence (mid-migration) is outside the
                # scheduler's queues; an abort (client disconnect during the
                # handoff window) must still free it or it leaks forever
                frozen = self._frozen.pop(item[1], None)
                if frozen is not None and not frozen.finished:
                    self.scheduler._finish(frozen, "abort")
                    self._emit(frozen, "")
                for s in self.scheduler.waiting + self.scheduler.running:
                    if s.seq_id == item[1] and not s.finished:
                        self.scheduler._finish(s, "abort")
                        # deliver the terminal output: a router-initiated
                        # abort (POST /abort) has a consumer still blocked on
                        # out_q.get() — without this it would wait forever
                        # even though the slot and pages are already freed
                        self._emit(s, "")
            else:
                self._inbox_accept(item)

    def _inbox_accept(self, seq: Sequence) -> None:
        self._arrival_times.append(time.monotonic())
        if self._sleeping:
            # a request can pass generate()'s sleeping check on the event loop
            # just as sleep flips the flag on the device thread; it must be
            # answered, not parked in the scheduler until wake
            seq.finished = True
            self._emit(seq, "", error=True)
            return
        sched = self.scheduler
        # authoritative queue bound: the API layer's saturation check races
        # a burst of arrivals (it reads scheduler state the inbox hasn't
        # drained into yet), so the bound is ENFORCED here on the device
        # thread — same free-seat projection (scheduler.saturated).
        # shed_exempt sequences (parallel-sampling siblings of an admitted,
        # mid-flight request — see Sequence.shed_exempt) bypass it:
        # admission control gates requests, not choices.
        if sched.saturated(seq.priority) and not seq.shed_exempt:
            sched._finish(seq, "shed")
            self.requests_shed["queue_full"] += 1
            self.requests_shed_by_class[
                seq.priority if seq.priority in self.requests_shed_by_class
                else "interactive"
            ] += 1
            self._note_shed("queue_full", seq)
            self._emit(seq, "")
            return
        sched.add(seq)

    def _run_loop(self) -> None:
        logger.info("engine loop started (model=%s)", self.cfg.name)
        while not self._stop.is_set():
            if self._sleeping:
                time.sleep(0.05)
                self._drain_inbox(block=False)
                continue
            t_sec = time.perf_counter()
            self._drain_inbox(block=not self.scheduler.has_work())
            self._shed_expired()  # queue-deadline load shedding
            if self.warm is not None:
                # periodic warm-start manifest (crash protection): prefers
                # idle loop iterations, forced past 2x the interval
                self.warm.maybe_spill(busy=self.scheduler.has_work())
            # adaptive chain depth inputs: the scheduler caps chained bursts
            # so the expected number of arrivals stuck waiting behind a chain
            # stays below ~half a request (scheduler.schedule)
            self.scheduler.arrival_rate = self._recent_arrival_rate()
            self.scheduler.burst_seconds = self._burst_seconds
            self.scheduler.last_arrival_age = (
                time.monotonic() - self._arrival_times[-1]
                if self._arrival_times else float("inf")
            )
            t0 = time.perf_counter()
            self.loop_seconds["wait"] += t0 - t_sec
            batch = self.scheduler.schedule()
            self.loop_seconds["schedule"] += time.perf_counter() - t0
            if batch is None:
                continue
            self._record_sched_event(batch)
            if batch.kind == "prefill":
                self._note_first_dispatch(batch)
            fetched = True
            lp_data = None  # (chosen [B, cols], top_ids, top_lp [B, cols, K])
            t_step = time.perf_counter()
            # apply/emit seconds booked inline (incremental chained fetch)
            # this iteration — excluded from the step/chain_fetch sections
            # so the loop_seconds breakdown stays disjoint and sums to wall
            inline_ae = 0.0
            try:
                inp = StepInput(
                    batch.input_ids, batch.positions, batch.page_table,
                    batch.kv_lens, batch.temperature, batch.top_k, batch.top_p,
                    lora_ids=batch.lora_ids, kv_limits=batch.kv_limits,
                )
                if batch.want_penalties:
                    inp.history = batch.history
                    inp.prompt_lens = batch.prompt_lens
                    inp.presence = np.array(
                        [s.params.presence_penalty for s in batch.seqs]
                        + [0.0] * (len(batch.kv_lens) - len(batch.seqs)),
                        np.float32,
                    )
                    inp.frequency = np.array(
                        [s.params.frequency_penalty for s in batch.seqs]
                        + [0.0] * (len(batch.kv_lens) - len(batch.seqs)),
                        np.float32,
                    )
                    inp.repetition = np.array(
                        [s.params.repetition_penalty for s in batch.seqs]
                        + [1.0] * (len(batch.kv_lens) - len(batch.seqs)),
                        np.float32,
                    )
                # rows still under their min_tokens floor get EOS masked out
                # of the sampled distribution (vLLM semantics — suppressing
                # only the FINISH would feed a sampled EOS back into the
                # context and derail the continuation). Conservative within
                # a dispatch: the ban holds for ALL the tokens one dispatch
                # covers, and the scheduler caps chaining for rows near the
                # floor (scheduler.schedule), so the overshoot stays
                # < decode_steps regardless of pipeline depth; the
                # scheduler's finish gate stays as the exact backstop.
                eos = self.tokenizer.eos_token_id
                def _eos_ban(s):
                    return (
                        not s.params.ignore_eos
                        and len(s.output_ids) < s.params.min_tokens
                    )
                if any(s.params.logit_bias or _eos_ban(s) for s in batch.seqs):
                    B = len(batch.kv_lens)
                    # bucket the bias width so a batch's entry count doesn't
                    # mint a fresh program variant per distinct size
                    need = max(
                        len(s.params.logit_bias or {}) + (1 if _eos_ban(s) else 0)
                        for s in batch.seqs
                    )
                    K = 8
                    while K < need:
                        K *= 2
                    V = self.model_cfg.vocab_size
                    # out-of-range sentinel V drops unused slots on device
                    bias_ids = np.full((B, K), V, np.int32)
                    bias_vals = np.zeros((B, K), np.float32)
                    for i, s in enumerate(batch.seqs):
                        j = 0
                        for tid, bv in (s.params.logit_bias or {}).items():
                            bias_ids[i, j] = tid
                            bias_vals[i, j] = bv
                            j += 1
                        if _eos_ban(s):
                            bias_ids[i, j] = eos
                            bias_vals[i, j] = -1e9
                    inp.bias_ids, inp.bias_vals = bias_ids, bias_vals
                if (
                    batch.kind == "decode"
                    and self.scheduler.spec_k
                    and batch.history is not None
                ):
                    tokens = np.asarray(
                        self.runner.step_spec(
                            inp, batch.history, self.scheduler.decode_steps,
                            self.scheduler.spec_k, self.scheduler.spec_ngram,
                        )
                    )  # [B, steps, 1+spec_k], -1 padded
                    emitted = tokens >= 0
                    rounds = int(emitted.any(axis=2).sum())
                    self.spec_draft_tokens += rounds * self.scheduler.spec_k
                    # each round emits its accepted drafts plus one bonus token
                    self.spec_accepted_tokens += int(emitted.sum()) - rounds
                elif batch.kind == "decode" and self.scheduler.decode_steps > 1:
                    wlp = batch.want_logprobs
                    self.decode_dispatches_total += 1
                    if batch.bursts > 1:
                        self.decode_chained_dispatches_total += 1
                        t_chain = time.perf_counter()
                        # chained bursts: all dispatches go out before any
                        # fetch, so the chain costs bursts*compute + 1 round
                        # trip for the LAST burst only.
                        devs = self.runner.step_multi_pipelined(
                            inp, self.scheduler.decode_steps, batch.bursts,
                            wlp,
                            # grouped on-device concat + eager host copy at
                            # each 4-burst boundary (see runner docstring);
                            # the logprobs path still fetches whole-chain
                            fetch_group=0 if wlp else 4,
                        )
                        t_disp = time.perf_counter()
                        self.loop_seconds["chain_dispatch"] += t_disp - t_chain
                        import jax.numpy as jnp

                        if wlp:
                            import jax

                            # one pytree fetch: device_get starts all four
                            # copies together (~1 RTT), where sequential
                            # np.asarray calls would pay one RTT each
                            tokens, *lps = jax.device_get((
                                jnp.concatenate([d[0] for d in devs], axis=1),
                                *(jnp.concatenate([d[1][x] for d in devs], axis=1)
                                  for x in range(3)),
                            ))
                            lp_data = tuple(lps)
                        else:
                            # incremental grouped fetch: the runner already
                            # enqueued each group's on-device concat at its
                            # burst boundary and started its host copy, so
                            # group j's tokens stream back while groups
                            # j+1.. still compute — the fetch RTT (and the
                            # ~50 ms per-RPC floor, amortized 4x) hides
                            # inside the chain's own compute, and clients
                            # get a chunk per group instead of one
                            # chain-sized batch. Applying group j before
                            # j+1 lands is safe: a row that finishes
                            # (EOS/stop) keeps computing masked/discarded
                            # tokens, its freed pages cannot be reallocated
                            # until the next schedule() (this thread), and
                            # the garbage tokens write past the region the
                            # prefix cache registered.
                            gcats = devs
                            # run-ahead: admit fresh arrivals and dispatch
                            # their prefill chunks NOW — the device queues
                            # them straight behind the chain's bursts
                            # instead of idling through the chain's fetch +
                            # scheduling turnaround. Aborts are deferred
                            # (see _drain_inbox) so no page freed under the
                            # in-flight chain can be re-allocated here.
                            ra_done, ra_inter = self._runahead_prefills(batch)
                            ae0 = (self.loop_seconds["apply"]
                                   + self.loop_seconds["emit"])
                            for c in gcats:
                                self._apply_and_emit(batch, np.asarray(c))
                            # the chain's fetches retire dispatches QUEUED
                            # BEFORE the chain; run-ahead intermediates came
                            # after, so they stay suspect until the next
                            # fetch unless a run-ahead final fetch follows
                            self._unfetched = ra_inter
                            for ra, ids in ra_done:
                                self._apply_and_emit(ra, np.asarray(ids))
                            if ra_done:
                                self._unfetched = []
                            inline_ae = (
                                self.loop_seconds["apply"]
                                + self.loop_seconds["emit"] - ae0
                            )
                            fetched = False  # retirement handled above
                            tokens = None  # processed inline
                        self.loop_seconds["chain_fetch"] += (
                            time.perf_counter() - t_disp - inline_ae
                        )
                        # per-burst wall time EMA (includes fetch + apply +
                        # emit amortized over the chain — a mild
                        # overestimate, erring toward shorter chains and so
                        # better TTFT under arrivals)
                        dt = (time.perf_counter() - t_chain) / batch.bursts
                        self._burst_seconds = (
                            0.7 * self._burst_seconds + 0.3 * dt
                        )
                    elif wlp:
                        toks, lps = self.runner.step_multi(
                            inp, self.scheduler.decode_steps, True
                        )
                        tokens = np.asarray(toks)
                        lp_data = tuple(np.asarray(x) for x in lps)
                    else:
                        tokens = np.asarray(
                            self.runner.step_multi(inp, self.scheduler.decode_steps)
                        )  # [B, k]
                elif batch.kind == "prefill" and not any(
                    s.num_computed + c >= len(s.prompt_ids)
                    for s, c in zip(batch.seqs, batch.chunk_sizes)
                ):
                    # every chunk in this step is intermediate — nobody's
                    # prompt completes, so the sampled tokens are discarded
                    # anyway. Dispatch async and skip the host fetch: on
                    # network-attached TPUs each fetch is a full host<->device
                    # round trip, so an N-chunk prefill costs N*compute + 1 RTT
                    # instead of N*(compute + RTT). A deferred device error
                    # surfaces at the next fetched step; _unfetched records
                    # whose KV state is then suspect so the handler can abort
                    # them too, not just the batch it surfaced on.
                    self.runner.step(inp)
                    self._unfetched.append(batch)
                    fetched = False
                    tokens = np.full((len(batch.seqs),), -1, np.int32)
                elif batch.want_logprobs:
                    ids, _, lps = self.runner.step(inp, want_logprobs=True)
                    tokens = np.asarray(ids)
                    lp_data = tuple(np.asarray(x)[:, None] for x in lps)
                else:
                    ids, _ = self.runner.step(inp)
                    tokens = np.asarray(ids)
            except Exception as step_err:
                logger.exception("engine step failed; aborting batch")
                # postmortem: the window of scheduler/KV/compile events that
                # led INTO this failure, while it is still in the ring
                self._fr.record(
                    "error", step=self.step_idx, batch_kind=batch.kind,
                    error=repr(step_err)[:500],
                )
                self._fr.dump("engine_step_error", force=True)
                if self.cfg.distributed_num_processes > 1:
                    # multi-host: catch-and-continue would leave the leader
                    # serving while followers are dead or desynced (a broadcast
                    # happens before local execution). Exit so K8s restarts the
                    # StatefulSet and the set re-rendezvouses — this enforces
                    # the documented failure model (distributed.py).
                    logger.critical(
                        "fatal in multi-host mode: exiting so the pod set "
                        "restarts in sync"
                    )
                    os._exit(13)
                # deferred errors from skipped-fetch prefill dispatches
                # surface here: those sequences' KV is suspect, abort them too
                suspect = list(batch.seqs)
                for b in self._unfetched:
                    suspect.extend(b.seqs)
                self._unfetched.clear()
                for s in suspect:
                    if not s.finished:
                        self.scheduler._finish(s, "error")
                        self._emit(s, "", error=True)
                continue
            step_wall = time.perf_counter() - t_step - inline_ae
            self.loop_seconds["step"] += step_wall
            if self._fr.enabled:
                # runner step timing, dispatch-granular: a fetched step's
                # wall is real device time; a skip-fetch dispatch's wall is
                # enqueue-only (the trailing fetched step absorbs its compute)
                self._fr.record(
                    "step", step=self.step_idx, batch_kind=batch.kind,
                    wall_ms=round(step_wall * 1000, 3), bursts=batch.bursts,
                    fetched=fetched,
                )
            if fetched:
                self._unfetched.clear()  # a real fetch retires prior dispatches
                # dispatch-granular prefill-phase observability (the
                # Grafana prefill panel): chunk latency for FETCHED prefill
                # dispatches (a skip-fetch dispatch's wall is just enqueue
                # time — the final fetched chunk absorbs the queued
                # compute), and decode per-token time while a prefill is
                # resident (the interleave the demand gate schedules)
                if batch.kind == "prefill":
                    tracing.prefill_chunk_hist.observe(step_wall)
                elif batch.kind == "decode" and any(
                    s.in_prefill for s in self.scheduler.running
                ):
                    toks_n = max(
                        1, self.scheduler.decode_steps * batch.bursts
                    )
                    tracing.interleaved_decode_hist.observe(
                        step_wall / toks_n
                    )
            if tokens is not None:
                self._apply_and_emit(batch, tokens, lp_data)
        logger.info("engine loop exited")

    def _record_sched_event(self, batch) -> None:
        """Flight-recorder "sched" event: the batch composition and the
        interleave-gate inputs that produced it, stamped with the step index
        and the members' trace ids so a slow request's spans cross-link to
        the exact dispatches that served (or starved) it."""
        self.step_idx += 1
        fr = self._fr
        if not fr.enabled:
            return
        trace_ids = [
            s.trace.trace_id
            for s in batch.seqs
            if s.trace is not None and getattr(s.trace, "sampled", False)
        ][:4]
        fr.record(
            "sched", step=self.step_idx, batch_kind=batch.kind,
            tp=self.tensor_parallel,
            rows=len(batch.seqs), bursts=batch.bursts,
            chunk_tokens=sum(batch.chunk_sizes) if batch.chunk_sizes else 0,
            seq_ids=[s.seq_id for s in batch.seqs[:8]],
            trace_ids=trace_ids,
            gate=getattr(self.scheduler, "last_gate", None),
            running=self.scheduler.num_running(),
            waiting=self.scheduler.num_waiting(),
            kv_usage=round(self.kv.usage(), 4),
            trace_id=trace_ids[0] if trace_ids else None,
        )

    def _note_first_dispatch(self, batch) -> None:
        """Record the admission-wait hop (arrival -> first prefill dispatch)
        for rows reaching the device for the first time — in the main loop
        or via run-ahead."""
        now = time.monotonic()
        for s in batch.seqs:
            if s.first_dispatch_time is None:
                s.first_dispatch_time = now
                self.admission_wait_ms.append((now - s.arrival_time) * 1000)

    @staticmethod
    def _runahead_allowed(s: Sequence) -> bool:
        """Rows whose dispatch needs no bias/penalty/logprob staging — that
        staging lives on the normal path only; others wait for it."""
        return (
            not s.params.wants_penalties
            and s.params.logprobs is None
            and not s.params.logit_bias
            and (s.params.ignore_eos
                 or len(s.output_ids) >= s.params.min_tokens)
        )

    def _runahead_prefills(self, chain_batch):
        """Dispatch prefill work for sequences disjoint from an in-flight
        decode chain (the device queues it behind the chain's bursts — zero
        idle). Returns (final_dispatches_to_fetch, intermediate_batches).
        Stops at the first final-chunk dispatch so a single trailing fetch
        retires every intermediate before it. Deferred aborts are re-queued
        HERE, before anything can raise — they are only processed at the
        next ordinary inbox drain, after the chain has been applied."""
        for item in self._drain_inbox(block=False, defer_aborts=True):
            self._inbox.put(item)
        ra_done: list = []
        ra_inter: list = []
        if self._sleeping:
            return ra_done, ra_inter
        exclude = {id(s) for s in chain_batch.seqs}
        for _ in range(4):  # bound the work queued behind one chain
            ra = self.scheduler.schedule_prefill_runahead(
                exclude, allow=self._runahead_allowed
            )
            if ra is None:
                break
            self._record_sched_event(ra)
            self._note_first_dispatch(ra)
            self.runahead_prefill_dispatches_total += 1
            inp = StepInput(
                ra.input_ids, ra.positions, ra.page_table, ra.kv_lens,
                ra.temperature, ra.top_k, ra.top_p, lora_ids=ra.lora_ids,
                kv_limits=ra.kv_limits,
            )
            if not any(
                s.num_computed + c >= len(s.prompt_ids)
                for s, c in zip(ra.seqs, ra.chunk_sizes)
            ):
                # all-intermediate chunks: skip-fetch (same optimization as
                # the main loop) and account the progress immediately so the
                # next planning round sees it
                self.runner.step(inp)
                self._unfetched.append(ra)
                ra_inter.append(ra)
                self._apply_and_emit(
                    ra, np.full((len(ra.seqs),), -1, np.int32)
                )
            else:
                ids, _ = self.runner.step(inp)
                ra_done.append((ra, ids))
                break  # one trailing fetch retires all intermediates above
        return ra_done, ra_inter

    def _apply_and_emit(self, batch, tokens, lp_data=None) -> None:
        """Apply one fetched token matrix to scheduler state and stream the
        resulting deltas — called once per dispatch, or once per BURST for
        incrementally-fetched chains (the per-column apply is identical
        either way; scheduler.apply_step skips finished rows)."""
        t_apply = time.perf_counter()
        events = self.scheduler.apply_step(
            batch, tokens, self.tokenizer.eos_token_id
        )
        if batch.kind == "prefill":
            for s, c in zip(batch.seqs, batch.chunk_sizes):
                self.total_prompt_tokens += c
        if self._kv_sender is not None:
            # ship KV before emitting the finish event: the prefill HTTP
            # response must not return until the decode peer holds the KV
            pushed = set()
            for s, _, _, _ in events:
                if s.finished and s.seq_id not in pushed:
                    pushed.add(s.seq_id)
                    self._push_finished_kv(s)
        t_emit = time.perf_counter()
        self.loop_seconds["apply"] += t_emit - t_apply
        # group burst events per sequence: one RequestOutput per seq per
        # device step, carrying every new token (finished only on the
        # last, so consumers never drop trailing burst tokens)
        grouped: dict[str, tuple[Sequence, list[int], list]] = {}
        for s, tok, i, j in events:
            g = grouped.setdefault(s.seq_id, (s, [], []))
            g[1].append(tok)
            if lp_data is not None and s.params.logprobs is not None:
                n = min(s.params.logprobs, lp_data[1].shape[2])
                g[2].append({
                    "logprob": float(lp_data[0][i, j]),
                    "top_ids": lp_data[1][i, j, :n].tolist(),
                    "top_logprobs": lp_data[2][i, j, :n].tolist(),
                })
        for s, toks, lps in grouped.values():
            self.total_generation_tokens += len(toks)
            self._process_token(s, toks, lps or None)
        self.loop_seconds["emit"] += time.perf_counter() - t_emit

    def _push_finished_kv(self, seq: Sequence) -> None:
        """Producer role: push every hashed page of a finished sequence to the
        decode peer. Runs on the device thread right after scheduler._finish
        registered the pages, so their pids are still valid (nothing else has
        allocated since)."""
        from production_stack_tpu.engine.kv_manager import prefix_hashes

        tokens = seq.prompt_ids + seq.output_ids
        hashes = list(prefix_hashes(tokens, self.kv.page_size, seq.cache_salt))
        if self._fabric_client is not None:
            # fabric-first: stream the whole chain as (pages, scales)
            # frames; anything the fabric could not cover falls through to
            # the per-page TCP-blob / device paths below (counted fallback)
            hashes = self._fabric_stream_push(hashes)
        for h in hashes:
            pid = self.kv.hash_to_page.get(h)
            if pid is None:
                continue
            key = h.hex()
            if self._kv_sender._mh_addrs is not None and not self.kv_quant:
                # device path (assignment protocol, single- or multi-host):
                # REPLICATED offer on every producer process, one pull
                # assignment per consumer process; nbytes from pool metadata
                # only — the page gather runs inside kv_offer_page AFTER the
                # consumer accepts, so refusals cost no device work. A
                # refused/failed page falls through to the TCP blob push.
                kp = self.runner.k_pages
                page_nbytes = 2 * (kp.nbytes // kp.shape[1])
                if self._kv_sender.push_device_multihost(key, page_nbytes, pid):
                    continue
            blob = None
            if self._offload is not None:
                blob = self._offload.store.get(key)
            if blob is None:
                if self.kv_quant:
                    # quantized pool: ship the exact pool bytes + scales
                    # (serde v3); the raw get_page path has no scales
                    from production_stack_tpu.kvoffload.serde import Int8PageSerde

                    ks, vs, sks, svs = self.runner.get_pages_quant([pid])
                    blob = Int8PageSerde().serialize_quant(
                        np.asarray(ks[0]), np.asarray(sks[0]),
                        np.asarray(vs[0]), np.asarray(svs[0]),
                    )
                else:
                    k, v = self.runner.get_page(pid)
                    serde = (
                        self._offload.serde
                        if self._offload is not None
                        else self._default_serde()
                    )
                    blob = serde.serialize(np.asarray(k), np.asarray(v))
            self._kv_sender.push(key, blob)

    def _default_serde(self):
        from production_stack_tpu.kvoffload.serde import get_serde

        return get_serde(self.cfg.kv_serde)

    # -- KV fabric plumbing ---------------------------------------------------

    def _fabric_gather(self, keys: "list[str]"):
        """Gather resident pages for hex ``keys`` off the device pool.
        Returns (found_keys, ks, vs, sks, svs) with host arrays; sks/svs are
        None on fp engines. MUST run on the device thread (replicated
        runner-dispatch discipline)."""
        found, pids = [], []
        for key in keys:
            try:
                pid = self.kv.hash_to_page.get(bytes.fromhex(key))
            except ValueError:
                pid = None
            if pid is not None:
                found.append(key)
                pids.append(pid)
        if not pids:
            return [], [], [], None, None
        if self.kv_quant:
            ks, vs, sks, svs = self.runner.get_pages_quant(pids)
            sks = [np.asarray(s) for s in sks]
            svs = [np.asarray(s) for s in svs]
        else:
            ks, vs = self.runner.get_pages(pids)
            sks = svs = None
        return (
            found,
            [np.asarray(k) for k in ks],
            [np.asarray(v) for v in vs],
            sks,
            svs,
        )

    def _fabric_pages(self, keys: "list[str]"):
        """Fabric listener pull handler: resident pages for ``keys`` as one
        encoded wire frame. Called on the listener's worker thread; the pool
        gather is marshalled onto the device thread."""
        from production_stack_tpu.kvfabric import wire as fabric_wire

        found, ks, vs, sks, svs = self._run_on_device_thread(
            lambda: self._fabric_gather(keys)
        )
        if not found:
            return [], b""
        frame = fabric_wire.encode_frame(
            found, ks, vs, sks, svs, nlayers=int(ks[0].shape[0])
        )
        return found, frame

    def _fabric_sink(self, frame: dict) -> int:
        """Fabric push handler: assemble layer windows into whole pages and
        land them as LOCAL tier blobs, where the ordinary admission/restore
        path (and migration's prefetch walk) finds them — zero shared-tier
        I/O. Quant frames keep their scales verbatim (serde v3 blob); the
        serde cross-dtype contract covers fp<->int8 engine pairs at restore
        time."""
        if self._offload is None:
            return 0
        from production_stack_tpu.kvoffload.serde import Int8PageSerde

        stored = 0
        for key, (k, v, sk, sv) in self._fabric_asm.add(frame):
            if sk is not None:
                blob = Int8PageSerde().serialize_quant(k, sk, v, sv)
            else:
                blob = self._offload.serde.serialize(k, v)
            self._offload.store.put_local(key, blob)
            stored += 1
        return stored

    def _resolve_fabric_peer(self) -> Optional[str]:
        """Fabric listener address of the disagg decode peer.
        ``--kv-fabric-peer`` is either the address itself ("host:port") or
        the peer's HTTP URL — then GET /kv_fabric resolves the advertised
        listener (the peer may bind an ephemeral port). Cached; cleared
        after a fabric failure so the next push re-resolves."""
        if self._fabric_peer_addr is not None:
            return self._fabric_peer_addr
        target = self.cfg.kv_fabric_peer
        if not target:
            return None
        addr = target
        if target.startswith("http"):
            try:
                import json as json_mod
                import urllib.request

                with urllib.request.urlopen(
                    target.rstrip("/") + "/kv_fabric", timeout=5
                ) as r:
                    info = json_mod.loads(r.read())
                addr = info.get("addr") if info.get("enabled", True) else None
            except Exception as e:  # noqa: BLE001 - fabric is optional
                logger.warning("fabric peer resolve failed for %s: %s", target, e)
                addr = None
        self._fabric_peer_addr = addr
        return addr

    def _fabric_stream_push(self, hashes: list) -> list:
        """Streamed disagg prefill: ship a finished prefill's page chain to
        the decode peer as layer-windowed (pages, scales) frames
        (``--kv-fabric-stream-layers`` layers per frame), so the consumer
        starts landing pages before the last layer arrives — this replaces
        the shared-tier re-acquire of phase 1. Returns the hashes NOT
        covered (no peer, gather/push failure): the caller's TCP-blob path
        is the per-page fallback, counted on kv_fabric_fallbacks_total."""
        addr = self._resolve_fabric_peer()
        if addr is None:
            return hashes
        from production_stack_tpu.kvfabric import wire as fabric_wire

        try:
            found, ks, vs, sks, svs = self._fabric_gather(
                [h.hex() for h in hashes]
            )
        except Exception as e:  # noqa: BLE001 - fall back to TCP blobs
            logger.warning("fabric page gather failed: %s", e)
            self._fabric_client.count_fallback(len(hashes))
            return hashes
        if not found:
            return []
        nlayers = int(ks[0].shape[0])
        win = self.cfg.kv_fabric_stream_layers or nlayers
        ok = True
        for lo in range(0, nlayers, win):
            hi = min(lo + win, nlayers)
            frame = fabric_wire.encode_frame(
                found,
                [k[lo:hi] for k in ks],
                [v[lo:hi] for v in vs],
                [s[lo:hi] for s in sks] if sks is not None else None,
                [s[lo:hi] for s in svs] if svs is not None else None,
                layers=(lo, hi),
                nlayers=nlayers,
            )
            if not self._fabric_client.push(addr, frame):
                ok = False
                break
        if ok:
            return []
        # mid-stream failure: drop the cached peer (it may have restarted
        # on a new port) and let the TCP path re-ship the whole chain; the
        # consumer's assembler bounds any partial windows we left behind
        self._fabric_peer_addr = None
        self._fabric_client.count_fallback(len(found))
        return hashes

    def fabric_ship_pairs(
        self, addr: str, pairs: "list[tuple[int, str]]"
    ) -> "list[str]":
        """Ship explicit ``(pid, key_hex)`` pages to ``addr`` over the
        fabric — migration's freeze->ship path, where a frozen sequence's
        pages are not yet registered in hash_to_page (registration happens
        at finish). Returns the keys actually shipped. Safe from any
        thread: the gather marshals onto the device thread, and
        _run_on_device_thread is re-entrant for callers already on it (the
        freeze path)."""
        if self._fabric_client is None or not pairs:
            return []
        from production_stack_tpu.kvfabric import wire as fabric_wire

        def gather():
            pids = [p for p, _ in pairs]
            if self.kv_quant:
                ks, vs, sks, svs = self.runner.get_pages_quant(pids)
                sks = [np.asarray(s) for s in sks]
                svs = [np.asarray(s) for s in svs]
            else:
                ks, vs = self.runner.get_pages(pids)
                sks = svs = None
            return (
                [np.asarray(k) for k in ks],
                [np.asarray(v) for v in vs],
                sks,
                svs,
            )

        try:
            ks, vs, sks, svs = self._run_on_device_thread(gather)
        except Exception as e:  # noqa: BLE001 - tier save is the fallback
            logger.warning("fabric migration gather failed: %s", e)
            self._fabric_client.count_fallback(len(pairs))
            return []
        keys = [k for _, k in pairs]
        frame = fabric_wire.encode_frame(
            keys, ks, vs, sks, svs, nlayers=int(ks[0].shape[0])
        )
        if self._fabric_client.push(addr, frame):
            return keys
        self._fabric_client.count_fallback(len(pairs))
        return []

    def _process_token(
        self, seq: Sequence, new_tokens: list[int], logprobs: Optional[list] = None
    ) -> None:
        """Detokenize incrementally, check stop strings, emit the delta (with
        this step's new tokens — one or a whole decode burst; ``logprobs``
        aligns 1:1 with ``new_tokens`` when requested)."""
        raw = full = self.tokenizer.decode(seq.output_ids)
        if not seq.finished and full.endswith("�"):
            # hold back a trailing incomplete byte sequence (renders as
            # replacement chars) until later tokens complete it — emitting it
            # now would desync the incremental stream, and the emit boundaries
            # (per-token, burst, or speculative round) must not change the
            # streamed text. Held-back chars flush on the finishing emit.
            full = full.rstrip("�")
        if not seq.finished and seq.params.stop:
            # hold back a trailing PARTIAL stop-string match until later
            # tokens resolve it: a decode_steps=1 engine otherwise streams
            # the stop's first chars one token at a time (they cannot be
            # retracted once emitted), while a burst engine sees the whole
            # stop inside one dispatch and trims before it — the emitted
            # text must not depend on the dispatch boundary. A completed
            # stop is handled by the trim below; non-stop text flushes on
            # the finishing emit (gate above), exactly like the byte hold.
            hold = 0
            for s in seq.params.stop:
                for j in range(min(len(s) - 1, len(full)), hold, -1):
                    if full.endswith(s[:j]):
                        hold = j
                        break
            if hold:
                full = full[: len(full) - hold]
        # under _lock: generate()'s finally pops this entry from the event
        # loop concurrently (unlocked read found by graftcheck GC004)
        with self._lock:
            prev = self._texts.get(seq.seq_id, "")
        delta = full[len(prev):] if full.startswith(prev) else full
        if seq.params.stop and any(s in raw for s in seq.params.stop):
            # Stop detection must not depend on emission boundaries (per-token
            # vs burst vs chained bursts give the same stream): scan this
            # step's token prefixes and stop at the FIRST prefix whose decode
            # contains a stop string — exactly where a decode_steps=1 engine
            # detects it. The prefix scan is O(burst * output length)
            # detokenization, so it only runs once the full decode contains a
            # stop (a stop visible at some prefix is made of complete chars
            # and stays visible in the full text).
            base = len(seq.output_ids) - len(new_tokens)
            hit = None  # (keep, text_at_keep, stop_index)
            for m in range(1, len(new_tokens) + 1):
                txt = self.tokenizer.decode(seq.output_ids[: base + m])
                for stop in seq.params.stop:
                    idx = txt.find(stop)
                    if idx >= 0:
                        hit = (m, txt, idx)
                        break
                if hit:
                    break
            if hit:
                keep, txt, idx = hit
                delta = txt[len(prev): idx] if txt.startswith(prev) else txt[:idx]
                del seq.output_ids[base + keep:]
                # the loop already counted the whole burst
                self.total_generation_tokens -= len(new_tokens) - keep
                new_tokens = new_tokens[:keep]
                if logprobs is not None:
                    logprobs = logprobs[:keep]
                if not seq.finished:
                    self.scheduler._finish(seq, "stop")
                elif seq.finish_reason == "length":
                    # the length cap landed in the same step the stop text
                    # appeared; the emitted text ends at the stop, so report it
                    seq.finish_reason = "stop"
        with self._lock:
            # presence-gated: generate()'s finally may have popped the entry
            # since the read above (client abandoned the stream) — an
            # unconditional write would RESURRECT it, and with the only
            # removal site already run, leak the full text forever
            if seq.seq_id in self._texts:
                self._texts[seq.seq_id] = prev + delta
        self._emit(seq, delta, tokens=new_tokens, logprobs=logprobs)

    def _record_phase_trace(self, seq: Sequence) -> None:
        """Record the per-phase spans and histograms for a finished sequence.

        Phase boundaries come from timestamps the scheduler already keeps
        (arrival, first prefill dispatch, first token, finish), so this runs
        once per request at finish — zero cost on the step path. Histograms
        are always-on (they back the dashboard's phase panels); spans only
        when the request carries a sampled trace context."""
        seq.trace_done = True
        now_m = time.monotonic()
        anchor = time.time() - now_m  # monotonic -> wall clock
        end = seq.finish_time or now_m
        fd = seq.first_dispatch_time
        ft = seq.first_token_time
        queue_s = max(0.0, (fd if fd is not None else end) - seq.arrival_time)
        prefill_s = max(0.0, (ft - fd)) if fd is not None and ft is not None else 0.0
        decode_s = max(0.0, (end - ft)) if ft is not None else 0.0
        steps = len(seq.output_ids)
        tracing.queue_time_hist.observe(queue_s)
        if fd is not None and ft is not None:
            tracing.prefill_time_hist.observe(prefill_s)
        if ft is not None and steps > 1:
            tracing.decode_step_time_hist.observe(decode_s / (steps - 1))
        tr = seq.trace
        if tr is None or not getattr(tr, "sampled", False):
            return
        col = tracing.get_collector()
        # the scheduler pre-allocated the phase-span contexts at admission so
        # offload spill/restore spans could nest under the phase whose wall
        # window contains them; record the phases under those same contexts
        col.record(
            "engine.queue", seq.queue_span or tr.child(),
            anchor + seq.arrival_time, queue_s, seq_id=seq.seq_id,
        )
        if fd is not None and ft is not None:
            col.record(
                "engine.prefill", seq.prefill_span or tr.child(),
                anchor + fd, prefill_s,
                seq_id=seq.seq_id, prompt_tokens=len(seq.prompt_ids),
                cached_tokens=seq.num_cached,
            )
        if ft is not None:
            attrs = {
                "seq_id": seq.seq_id,
                "output_tokens": steps,
                "finish_reason": seq.finish_reason,
            }
            if steps > 1:
                attrs["per_token_ms"] = round(decode_s / (steps - 1) * 1000, 3)
            if seq.lora_slot:
                # LoRA sub-phase marker: which adapter slot served the decode
                attrs["lora_slot"] = seq.lora_slot
            if self.cfg.speculative_k:
                attrs["spec_k"] = self.cfg.speculative_k
            col.record(
                "engine.decode", seq.decode_span or tr.child(),
                anchor + ft, decode_s, **attrs,
            )

    def _record_slo(self, seq: Sequence, error: bool = False) -> None:
        """Attribute the finished sequence its SLO terminal record: queue
        wait, TTFT, token counts, inter-token p99, peak KV footprint, and the
        terminal outcome. Appended to the bounded ``slo_records`` log the
        router scrapes (GET /slo_records) and mirrored as a flight-recorder
        event so anomaly dumps carry the requests that were in flight."""
        seq.slo_done = True
        end = seq.finish_time or time.monotonic()
        fd, ft = seq.first_dispatch_time, seq.first_token_time
        reason = "error" if error else (seq.finish_reason or "error")
        outcome = (
            "ok" if reason in ("stop", "length", "tool_calls") else reason
        )
        itl_p99_ms = None
        if seq.itl_samples:
            s = sorted(seq.itl_samples)
            itl_p99_ms = round(
                s[min(len(s) - 1, int(len(s) * 0.99))] * 1000, 3
            )
        ttft_ms = (
            round((ft - seq.arrival_time) * 1000, 3) if ft is not None else None
        )
        rec = {
            "seq": next(self._slo_seq),
            "request_id": seq.seq_id,
            "model": self.cfg.name,
            "outcome": outcome,
            "finish_reason": reason,
            "queue_ms": round(((fd if fd is not None else end)
                               - seq.arrival_time) * 1000, 3),
            "ttft_ms": ttft_ms,
            "e2e_ms": round((end - seq.arrival_time) * 1000, 3),
            "prompt_tokens": len(seq.prompt_ids),
            "output_tokens": len(seq.output_ids),
            "cached_tokens": seq.num_cached,
            "itl_p99_ms": itl_p99_ms,
            "kv_pages_peak": seq.pages_peak,
            "trace_id": getattr(seq.trace, "trace_id", None),
            "priority": getattr(seq, "priority", "interactive"),
            "t": time.time(),
        }
        self.slo_records.append(rec)
        if outcome == "ok" and rec["priority"] == "interactive":
            if ttft_ms is not None:
                self._interactive_ttft_ms.append(ttft_ms)
            if itl_p99_ms is not None:
                self._interactive_itl_ms.append(itl_p99_ms)
        fr = self._fr
        if fr.enabled:
            fr.record(
                "slo", step=self.step_idx, trace_id=rec["trace_id"],
                request_id=seq.seq_id, outcome=outcome, ttft_ms=ttft_ms,
                itl_p99_ms=itl_p99_ms, output_tokens=rec["output_tokens"],
            )
            watermark = self.cfg.flight_recorder_ttft_watermark_ms
            if watermark > 0 and ttft_ms is not None and ttft_ms > watermark:
                fr.dump_async("ttft_breach")  # off the device thread

    def _emit(
        self,
        seq: Sequence,
        delta: str,
        tokens: Optional[list[int]] = None,
        error: bool = False,
        logprobs: Optional[list] = None,
    ) -> None:
        if tokens:
            # inter-token latency accounting for the SLO terminal record: a
            # burst emit of k tokens contributes its gap/k, so the p99 below
            # approximates what a streaming client measures. Capped — a long
            # stream must not grow an unbounded list (the p99 of the first
            # 4096 emits is representative; steady-state decode is stationary)
            now_m = time.monotonic()
            if seq.last_emit_time is not None and len(seq.itl_samples) < 4096:
                seq.itl_samples.append(
                    (now_m - seq.last_emit_time) / len(tokens)
                )
            seq.last_emit_time = now_m
        if seq.finished and not seq.trace_done:
            try:
                self._record_phase_trace(seq)
            except Exception:  # noqa: BLE001 - tracing must never break serving
                logger.exception("phase trace recording failed")
        if seq.finished and not seq.slo_done:
            try:
                self._record_slo(seq, error=error)
            except Exception:  # noqa: BLE001 - accounting must never break serving
                logger.exception("SLO terminal record failed")
        with self._lock:
            entry = self._outputs.get(seq.seq_id)
        if entry is None:
            return
        loop, out_q = entry
        out = RequestOutput(
            seq_id=seq.seq_id,
            text_delta=delta,
            token_ids=(
                tokens
                if tokens is not None
                else [seq.output_ids[-1]] if seq.output_ids else []
            ),
            finished=seq.finished,
            finish_reason=("error" if error else seq.finish_reason) if seq.finished else None,
            prompt_tokens=len(seq.prompt_ids),
            completion_tokens=len(seq.output_ids),
            cached_tokens=seq.num_cached,
            logprobs=logprobs,
        )
        loop.call_soon_threadsafe(out_q.put_nowait, out)

    # -- sleep / wake (engine contract: /sleep /wake_up /is_sleeping) -------

    def _lora_cmd(self, op: str, name: str, path: Optional[str] = None):
        """Run a LoRA load/unload. Device-buffer writes must not race the step
        loop (the slot update donates the live buffers), so when the engine
        loop is running the command is executed *by the device thread* between
        steps; otherwise it runs inline."""
        if self.lora is None:
            raise ValueError("LoRA is not enabled (--enable-lora)")

        if op == "load":
            # cheap prechecks before the (possibly large) checkpoint read;
            # load_parsed re-checks authoritatively under the manager lock
            from production_stack_tpu.engine.lora import LoRAError

            if self.lora.is_adapter(name):
                raise LoRAError(f"adapter {name!r} is already loaded")
            if not self.lora.has_free_slot():
                raise LoRAError(f"no free LoRA slots (max_loras={self.cfg.max_loras})")
            # parse on the caller thread: no disk I/O on the device thread
            tensors, scale = self.lora.read_checkpoint(path)

            def run():
                return self.lora.load_parsed(name, tensors, scale)
        else:
            def run():
                slot = self.lora.slot_for(name)  # 0 when not loaded
                in_use = slot != 0 and any(
                    s.lora_slot == slot
                    for s in self.scheduler.waiting + self.scheduler.running
                    if not s.finished
                )
                return self.lora.unload(name, in_use=in_use)

        return self._run_on_device_thread(run, what=f"LoRA {op} of {name!r}")

    def _run_on_device_thread(self, fn, what: str = "device command"):
        """Execute `fn` on the engine-loop thread between steps (device-state
        mutations and extra forwards must not race the step loop). Runs inline
        when the loop is not running."""
        if self._thread is None or not self._thread.is_alive():
            return fn()
        done = threading.Event()
        box: dict = {}

        def cmd():
            try:
                box["result"] = fn()
            except BaseException as e:  # surfaced on the caller thread
                box["error"] = e
            finally:
                done.set()

        self._inbox.put(("device_cmd", cmd))
        if not done.wait(timeout=120):
            raise TimeoutError(f"{what} timed out")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def load_lora_adapter(self, name: str, path: str) -> int:
        """Load a PEFT adapter; served under model name `name`.
        Contract parity: POST /v1/load_lora_adapter driven by the reference's
        LoraAdapter controller (loraadapter_controller.go:586-616)."""
        return self._lora_cmd("load", name, path)

    def unload_lora_adapter(self, name: str) -> None:
        """Unload an adapter. Refuses while requests using it are in flight
        (the controller retries), so a slot can never be re-targeted under a
        running sequence."""
        self._lora_cmd("unload", name)

    def list_lora_adapters(self) -> list[str]:
        return self.lora.list_adapters() if self.lora is not None else []

    _EMBED_T_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
    _EMBED_B_BUCKETS = (1, 2, 4, 8, 16, 32)

    async def embed(self, token_id_lists: list[list[int]]) -> np.ndarray:
        """Pooled unit-norm embeddings for a batch of tokenized inputs
        ([N, hidden_size] float32). Serves /v1/embeddings, /v1/rerank,
        /v1/score. Runs on the device thread, bucketed like generation."""
        if self._sleeping:
            raise RuntimeError("engine is sleeping")
        # capability check BEFORE the runner call: in multi-host mode every
        # runner.encode is broadcast to followers first, and a validation
        # error after broadcast desyncs the set (the wrapper treats it as
        # fatal) — a client request must never reach that path
        if not hasattr(self.runner.module, "encode"):
            raise ValueError(
                f"embeddings are not supported for model family "
                f"{self.runner.module.__name__.rsplit('.', 1)[-1]!r}"
            )
        for ids in token_id_lists:
            if len(ids) > self.cfg.max_model_len:
                raise ValueError(
                    f"input has {len(ids)} tokens, max_model_len is "
                    f"{self.cfg.max_model_len}"
                )

        def bucket(n, buckets):
            for b in buckets:
                if n <= b:
                    return b
            return buckets[-1]

        out = np.zeros((len(token_id_lists), self.model_cfg.hidden_size), np.float32)
        loop = asyncio.get_running_loop()
        # one device pass per B-bucket group of similar lengths
        order = sorted(range(len(token_id_lists)), key=lambda i: len(token_id_lists[i]))
        pos = 0
        while pos < len(order):
            group = order[pos : pos + self._EMBED_B_BUCKETS[-1]]
            pos += len(group)
            B = bucket(len(group), self._EMBED_B_BUCKETS)
            t_raw = max(max(len(token_id_lists[i]) for i in group), 1)
            T = bucket(t_raw, self._EMBED_T_BUCKETS)
            if T < t_raw:  # longer than the largest preset bucket: next pow2
                T = 1 << (t_raw - 1).bit_length()
            input_ids = np.zeros((B, T), np.int32)
            positions = np.full((B, T), -1, np.int32)
            for row, i in enumerate(group):
                ids = token_id_lists[i]
                input_ids[row, : len(ids)] = ids
                positions[row, : len(ids)] = np.arange(len(ids))
            def encode_cmd(input_ids=input_ids, positions=positions):
                if self._sleeping:  # may have gone to sleep since the check above
                    raise RuntimeError("engine is sleeping")
                return self.runner.encode(input_ids, positions)

            vecs = await loop.run_in_executor(
                None,
                lambda: np.asarray(
                    self._run_on_device_thread(encode_cmd, what="embedding forward")
                ),
            )
            for row, i in enumerate(group):
                out[i] = vecs[row]
            with self._lock:
                self.total_prompt_tokens += sum(
                    len(token_id_lists[i]) for i in group
                )
        return out

    def warm_spill(self) -> int:
        """Final warm-start manifest spill (SIGTERM drain path — the API
        server calls this after in-flight requests finish, before teardown).
        Runs on the device thread so the page fetches serialize with any
        still-running steps. No-op without --warm-start."""
        if self.warm is None:
            return 0
        try:
            return int(
                self._run_on_device_thread(
                    lambda: self.warm.spill("drain"), what="warm-start spill"
                ) or 0
            )
        except Exception:  # noqa: BLE001 - shutdown must not hang on a spill
            logger.exception("warm-start drain spill failed")
            return 0

    def sleep(self, level: int = 1) -> None:
        """Free HBM without killing the process. Level 1 drops the KV pools;
        level 2 additionally moves weights to host DRAM (SURVEY.md §7 hard
        part #5). Runs on the device thread, serialized with steps."""
        if self._sleeping:
            return

        def do_sleep():
            if self._sleeping:
                return  # raced with a concurrent sleep (handlers run on
                        # executor threads; only the device thread is serial)
            self._sleeping = True
            self._sleep_level = level
            for s in list(self.scheduler.running) + list(self.scheduler.waiting):
                self.scheduler._finish(s, "abort")
                self._emit(s, "")
            if self._kvdir_pub is not None and self.kv.hash_to_page:
                # dropping the pools invalidates every resident claim this
                # engine advertised; withdraw them or KV-aware v2 routers
                # keep resident-routing prompts at a cold sleeper (the idle
                # heartbeat would keep the stale claims alive forever).
                # Shared-tier claims stay — the blobs outlive the pools.
                self._kvdir_pub.withdraw(
                    list(self.kv.hash_to_page.keys()), "resident"
                )
            # replicated in multi-host: followers drop their pool shards too
            self.runner.drop_kv_pools()
            if level >= 2:
                # REPLICATED: every process offloads its own param shards to
                # its own host RAM, so level 2 works multi-host too
                self.runner.offload_params()
            import gc

            gc.collect()

        self._run_on_device_thread(do_sleep, what="sleep")

    def wake_up(self) -> None:
        if not self._sleeping:
            return

        def do_wake():
            if not self._sleeping:
                return  # raced with a concurrent wake
            if self._sleep_level >= 2:
                # REPLICATED: each process re-materializes its shards from
                # its own host copy (offload_params saved them)
                self.runner.restore_params()
            self.runner.reset_kv()  # replicated in multi-host
            self.kv = KVPageManager(
                self.kv.num_pages, self.kv.page_size, offload=self._offload,
                max_io_pages=self._max_io_pages,
                spill_watermark=self.cfg.kv_spill_watermark,
            )
            self.kv.directory = self._kvdir_pub  # keep fleet publishes alive
            if self._kvdir_pull is not None:
                self._kvdir_pull.kv = self.kv
            self.scheduler.kv = self.kv
            self._sleeping = False

        self._run_on_device_thread(do_wake, what="wake_up")

    @property
    def is_sleeping(self) -> bool:
        return self._sleeping

    # -- stats --------------------------------------------------------------

    def stats(self) -> dict:
        out = {
            "num_requests_running": self.scheduler.num_running(),
            "num_requests_waiting": self.scheduler.num_waiting(),
            "num_requests_swapped": self.scheduler.num_swapped(),
            "num_preemptions_total": self.scheduler.preemptions_total,
            "num_requests_shed_total": (
                sum(self.requests_shed.values()) + self.api_requests_shed
            ),
            "num_requests_shed_queue_full_total": (
                self.requests_shed["queue_full"] + self.api_requests_shed
            ),
            "num_requests_shed_queue_deadline_total": (
                self.requests_shed["queue_deadline"]
            ),
            # per-SLO-class shed counters (device-thread + event-loop writer
            # pairs summed, like num_requests_shed_total above)
            "num_requests_shed_interactive_total": (
                self.requests_shed_by_class["interactive"]
                + self.api_requests_shed_by_class["interactive"]
            ),
            "num_requests_shed_batch_total": (
                self.requests_shed_by_class["batch"]
                + self.api_requests_shed_by_class["batch"]
            ),
            "engine_saturated": int(self.saturated()),
            # batch-class saturation engages interactive_reserve slots early
            # — 1 here with engine_saturated 0 is the reserve protecting
            # interactive admission while batch already sheds
            "engine_saturated_batch": int(self.saturated("batch")),
            # serving-mesh shape: the router's scraper and the fleet
            # controller read these to reason about per-engine capacity (a
            # tp=4 engine is one replica on 4 chips, not 4 replicas)
            "tensor_parallel": self.tensor_parallel,
            "mesh_devices": self.mesh_devices,
            # KV quantization surface (docs/benchmarking.md byte-wall
            # model): pool bytes per token, quantized page count (= whole
            # pool when int8, 0 otherwise), and the startup dequant
            # round-trip error bound. cache_dtype is the string form for
            # GET /stats (non-numeric, so the /metrics kv_* sweep skips it)
            "cache_dtype": self.cfg.kv_cache_dtype,
            "kv_cache_dtype_bytes_per_token": round(self.kv_bytes_per_token, 3),
            "kv_quant_pages": self.kv.num_pages if self.kv_quant else 0,
            "kv_quant_dequant_err_max": round(self.kv_quant_dequant_err_max, 6),
            "gpu_cache_usage_perc": self.kv.usage(),
            "gpu_prefix_cache_hits_total": self.kv.prefix_hits,
            "gpu_prefix_cache_queries_total": self.kv.prefix_queries,
            "gpu_prefix_cache_hit_rate": self.kv.hit_rate(),
            "prompt_tokens_total": self.total_prompt_tokens,
            "generation_tokens_total": self.total_generation_tokens,
            "decode_dispatches_total": self.decode_dispatches_total,
            "decode_chained_dispatches_total": self.decode_chained_dispatches_total,
            "runahead_prefill_dispatches_total": (
                self.runahead_prefill_dispatches_total
            ),
        }
        for section, secs in self.loop_seconds.items():
            out[f"engine_loop_{section}_seconds_total"] = round(secs, 3)
        # interactive-SLO degradation signal for the fleet controller's
        # latency-protection policy (migration/controller.py): p99 over the
        # recent interactive ok-request window, 0.0 while idle
        for name, window in (
            ("interactive_ttft_p99_ms", self._interactive_ttft_ms),
            ("interactive_itl_p99_ms", self._interactive_itl_ms),
        ):
            snap = sorted(window)
            out[name] = (
                round(snap[min(len(snap) - 1, int(len(snap) * 0.99))], 3)
                if snap else 0.0
            )
        if self.cfg.speculative_k:
            # read accepted before drafts: the engine thread increments drafts
            # first, so this order keeps any unsynchronized snapshot at
            # accepted <= drafts (acceptance rate never exceeds 1.0)
            accepted = self.spec_accepted_tokens
            drafts = self.spec_draft_tokens
            out["spec_decode_num_draft_tokens_total"] = drafts
            out["spec_decode_num_accepted_tokens_total"] = accepted
            out["spec_decode_draft_acceptance_rate"] = (
                accepted / drafts if drafts else 0.0
            )
        if self._kv_sender is not None:
            out["kv_transfer_sent_chunks_total"] = self._kv_sender.sent_chunks
            out["kv_transfer_sent_bytes_total"] = self._kv_sender.sent_bytes
            out["kv_transfer_device_pages_total"] = self._kv_sender.device_pages
        if self._kv_receiver is not None:
            out["kv_transfer_received_chunks_total"] = self._kv_receiver.received_chunks
            out["kv_transfer_received_bytes_total"] = self._kv_receiver.received_bytes
            out["kv_transfer_device_pages_total"] = self._kv_receiver.device_pages
        if self._offload is not None and self._offload.device_staging is not None:
            out["kv_offload_device_loaded_pages_total"] = (
                self._offload.device_loaded_pages
            )
        ep = getattr(self.runner, "kv_endpoint", None)
        if ep is not None:
            # offer-retirement observability (transfer.py sweep): pinned HBM
            # and the upper bound on unpulled-offer leaks
            out["kv_transfer_pinned_offer_bytes"] = ep.pinned_offer_bytes()
            out["kv_transfer_leaked_offers_total"] = ep.leaked_offers
            out["kv_transfer_cap_evicted_offers_total"] = ep.cap_evicted_offers
        # eviction-policy observability (hot-prefix protection): total page
        # evictions, evictions that hit a page with a nonzero reuse count
        # (hot-set casualties — the "protected-page evictions" panel), and
        # pages spilled ahead of eviction by the high-watermark path
        out["kv_evicted_pages_total"] = self.kv.evicted_pages_total
        out["kv_evicted_hot_pages_total"] = self.kv.evicted_hot_pages_total
        out["kv_proactive_spilled_pages_total"] = (
            self.kv.proactive_spilled_pages_total
        )
        if self._offload is not None:
            o = self._offload.stats()
            out["kv_offload_hit_pages_total"] = self.kv.offload_hits
            out["kv_offload_saved_pages_total"] = o["saved_pages"]
            out["kv_offload_loaded_pages_total"] = o["loaded_pages"]
            out["kv_offload_cpu_bytes"] = o["cpu_bytes"]
            out["kv_offload_disk_bytes"] = o["disk_bytes"]
            # offload-tier integrity: blobs that failed their checksum on
            # read and were quarantined (never served) — local tiers plus,
            # on a disagg consumer, pushes rejected at the receiver
            corrupt = o.get("corrupt_pages", 0)
            if self._kv_receiver is not None:
                corrupt += getattr(self._kv_receiver, "corrupt_chunks", 0)
            out["kv_corrupt_pages_total"] = corrupt
            # permanent KV loss at the bottom local tier (satellite: was a
            # silent drop) — nonzero means blobs left the hierarchy entirely
            out["kv_offload_dropped_evictions_total"] = o.get(
                "dropped_evictions", 0
            )
            # offload I/O budget provenance: the active cap and, when the
            # startup probe chose it, the measured link bandwidth
            out["kv_offload_max_io_pages"] = self.kv.max_io_pages
            if self.kv_link_bandwidth_bytes_per_s is not None:
                out["kv_offload_link_bandwidth_bytes_per_sec"] = round(
                    self.kv_link_bandwidth_bytes_per_s
                )
        if self._kvdir_pub is not None:
            # fleet-directory surface (docs/kv-directory.md): publish-side
            p = self._kvdir_pub.stats()
            out["kv_directory_publishes_total"] = p["kv_directory_publishes_total"]
            out["kv_directory_withdrawals_total"] = (
                p["kv_directory_withdrawals_total"]
            )
            out["kv_directory_flush_errors_total"] = (
                p["kv_directory_flush_errors_total"]
            )
        if self.cfg.warm_prefetch_on_boot > 0:
            # scale-up warm-up surface (docs/migration.md): chunks pulled
            # into the local tiers before /ready
            out["kv_directory_prefetched_pages_total"] = (
                self.kv_directory_prefetched_pages
            )
        if self._kvdir_pull is not None:
            # ...and pull-side: lookups/hits drive the cross-engine pull
            # hit-rate panel; pulled pages are blobs fetched into local tiers
            q = self._kvdir_pull.stats()
            out["kv_directory_lookups_total"] = q["kv_directory_lookups_total"]
            out["kv_directory_lookup_hits_total"] = (
                q["kv_directory_lookup_hits_total"]
            )
            out["kv_directory_pulled_pages_total"] = (
                q["kv_directory_pulled_pages_total"]
            )
        if self._fabric_server is not None or self._fabric_client is not None:
            # KV fabric surface (docs/kv-fabric.md): push/pull volume, the
            # tier fallbacks every fabric path is allowed to take, corrupt
            # frames quarantined on either side, generation-fenced stale
            # pulls, and the live op depth peers fold into transfer-cost
            # scores (peers.transfer_cost_score)
            srv = self._fabric_server.stats() if self._fabric_server else {}
            cli = self._fabric_client.stats() if self._fabric_client else {}
            out["kv_fabric_pushed_pages_total"] = cli.get("pushed_pages", 0)
            out["kv_fabric_pulled_pages_total"] = cli.get("pulled_pages", 0)
            out["kv_fabric_served_pages_total"] = srv.get("served_pages", 0)
            out["kv_fabric_received_pages_total"] = srv.get("received_pages", 0)
            out["kv_fabric_fallbacks_total"] = cli.get("fallbacks", 0)
            out["kv_fabric_corrupt_frames_total"] = (
                cli.get("corrupt_frames", 0) + srv.get("corrupt_frames", 0)
            )
            out["kv_fabric_stale_generation_pulls_total"] = srv.get(
                "stale_generation_pulls", 0
            )
            out["kv_fabric_breaker_opens_total"] = cli.get("breaker_opens", 0)
            out["kv_fabric_peer_probes_total"] = cli.get("probes", 0)
            out["kv_fabric_queue_depth"] = srv.get("queue_depth", 0)
        if self.warm is not None:
            out.update(self.warm.stats())
        return out
