"""ModelRunner — owns device state (params, KV page pools) and the jitted step.

One compiled program per (batch_bucket, chunk_bucket, pages_bucket) triple; the
scheduler quantizes work to those buckets so XLA never sees a new shape in
steady state. KV pools are donated every call, so XLA updates pages in place
(no pool-sized copies per token).

This is the layer the reference delegates to vLLM's model executor; the serving
contract above it (engine/api_server.py) matches the stack's expectations
(SURVEY.md §1 L4).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu import models
from production_stack_tpu.ops.attention import write_kv_pages_all_layers
from production_stack_tpu.ops.sampling import (
    apply_logit_bias,
    apply_penalties,
    sample,
    sample_with_logprobs,
)
from production_stack_tpu.parallel import shardings
from production_stack_tpu.parallel.mesh import make_mesh


@dataclasses.dataclass
class StepInput:
    """Host-side batch description, already bucketed by the scheduler."""

    input_ids: Any      # [B, T] int32
    positions: Any      # [B, T] int32, -1 pad
    page_table: Any     # [B, max_pages] int32
    kv_lens: Any        # [B] int32 (including this step's tokens)
    temperature: Any    # [B] float32
    top_k: Any          # [B] int32
    top_p: Any          # [B] float32
    lora_ids: Any = None  # [B] int32 adapter slot (0 = base); None when LoRA off
    kv_limits: Any = None  # [B] int32 max kv_len (multi-step decode bound)
    # sampling penalties (set together when any row has penalties):
    history: Any = None      # [B, H] int32 prompt+output ids, position-indexed
    prompt_lens: Any = None  # [B] int32
    presence: Any = None     # [B] f32
    frequency: Any = None    # [B] f32
    repetition: Any = None   # [B] f32
    # OpenAI logit_bias (set together when any row has one):
    bias_ids: Any = None     # [B, K] int32 token ids, >= vocab_size = unused
    bias_vals: Any = None    # [B, K] f32 additive biases


class ModelRunner:
    """Holds params + KV pools on device and runs jitted prefill/decode steps."""

    def __init__(
        self,
        cfg,
        *,
        mesh: Optional[Mesh] = None,
        params: Optional[dict] = None,
        num_pages: int = 512,
        page_size: int = 16,
        seed: int = 0,
        module=None,
        enable_lora: bool = False,
        max_loras: int = 4,
        max_lora_rank: int = 16,
        lora_targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
    ):
        self.module = module if module is not None else models.module_for_config(cfg)
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.mesh = mesh if mesh is not None else make_mesh()
        mesh_shape = dict(self.mesh.shape)
        self._sp = mesh_shape.get("sp", 1)
        self._pp = mesh_shape.get("pp", 1)
        import inspect

        fwd_takes_mesh = (
            "mesh" in inspect.signature(self.module.forward).parameters
        )
        # which mesh axes the family's forward actually implements (a mesh
        # kwarg alone doesn't imply ring attention / pipeline support)
        mesh_axes = getattr(
            self.module, "MESH_AXES",
            ("dp", "tp") if fwd_takes_mesh else (),
        )
        if (self._sp > 1 and "sp" not in mesh_axes) or (
            self._pp > 1 and "pp" not in mesh_axes
        ):
            raise ValueError(
                f"model family {self.module.__name__.rsplit('.', 1)[-1]!r} "
                "does not support sequence/pipeline parallelism"
            )
        if self._sp > 1 or self._pp > 1:
            if self._pp > 1 and cfg.num_layers % self._pp:
                raise ValueError(
                    f"pipeline_parallel_size={self._pp} must divide "
                    f"num_layers={cfg.num_layers}"
                )
        if cfg.attn_impl == "auto":
            # pallas decode kernel on real TPU (the sharded path runs it per
            # shard via shard_map — ops/pallas/paged_attention.py). sp/ep
            # axes are mapped replicated (decode activations don't shard
            # over them); pp calls the kernel inside the pipeline's manual
            # region with stage-local layer pools. GSPMD alone cannot
            # partition a pallas_call, which is why every multi-device case
            # must reach the kernel through shard_map (fwd_takes_mesh).
            mesh_ok = self.mesh.devices.size == 1 or fwd_takes_mesh
            # the sharded kernel's shard_map specs split heads over tp
            # (NH/KH) — uneven head counts (e.g. 2 KV heads at tp=4) only
            # work on the XLA/GSPMD gather path, which tolerates padding
            tp = mesh_shape.get("tp", 1)
            heads_ok = (
                getattr(cfg, "num_heads", 1) % tp == 0
                and getattr(cfg, "num_kv_heads", 1) % tp == 0
            )
            use_pallas = jax.default_backend() == "tpu" and mesh_ok and heads_ok
            # "pallas_prefill": decode kernel everywhere it applies PLUS the
            # v2 chunked-prefill kernel (ragged packed grid + contiguous-KV
            # DMA ring + fused paged-KV write) on single-device prefill
            # chunks; multi-device prefill keeps the XLA/ring path inside
            # the model forward (GSPMD cannot partition a pallas_call)
            cfg = dataclasses.replace(
                cfg, attn_impl="pallas_prefill" if use_pallas else "xla"
            )
            self.cfg = cfg
        # the forward needs the mesh for sp/pp and for the sharded pallas
        # decode path on multi-device meshes
        needs_mesh = self._sp > 1 or self._pp > 1 or (
            cfg.attn_impl.startswith("pallas") and self.mesh.devices.size > 1
        )
        if needs_mesh and not fwd_takes_mesh:
            raise ValueError(
                f"model family {self.module.__name__.rsplit('.', 1)[-1]!r} "
                f"does not support attn_impl={cfg.attn_impl!r} on a "
                "multi-device mesh"
            )
        self._forward = (
            functools.partial(self.module.forward, mesh=self.mesh)
            if needs_mesh
            else self.module.forward
        )
        # deferred-scatter decode bursts (kv_burst): pools stay read-only
        # through the burst scan — requires post write mode and a family
        # whose forward takes the accumulator; pp relays KV stage-to-stage
        # and keeps the classic block-carry path
        self._kv_burst_ok = (
            "kv_burst" in inspect.signature(self.module.forward).parameters
            and getattr(cfg, "kv_write_mode", "pre") == "post"
            and self._pp == 1
        )

        # KV cache dtype (ops/quant.py): "auto" = model dtype; "bf16"/"fp16"
        # pin an explicit fp pool dtype; "int8" stores quantized pages plus
        # per-page per-kv-head scales pools — half the decode byte stream,
        # double the effective pool capacity
        kvdt = str(getattr(cfg, "kv_cache_dtype", "auto") or "auto")
        known = {
            "auto": None, "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
            "fp16": jnp.float16, "float16": jnp.float16, "int8": jnp.int8,
        }
        if kvdt not in known:
            raise ValueError(
                f"unknown kv_cache_dtype {kvdt!r}; options: {sorted(known)}"
            )
        self.kv_quant = kvdt == "int8"
        self.kv_pool_dtype = known[kvdt] or getattr(cfg, "dtype", jnp.bfloat16)
        if self.kv_quant:
            fwd_params = inspect.signature(self.module.forward).parameters
            if "kv_scales" not in fwd_params:
                raise ValueError(
                    f"model family {self.module.__name__.rsplit('.', 1)[-1]!r} "
                    "does not support kv_cache_dtype=int8"
                )
            if getattr(cfg, "kv_write_mode", "pre") != "post":
                raise ValueError(
                    "kv_cache_dtype=int8 requires kv_write_mode='post'"
                )
            if not self._kv_burst_ok:
                raise ValueError(
                    "kv_cache_dtype=int8 requires the deferred-burst decode "
                    "path (post write mode, kv_burst-capable family)"
                )
            if self._sp > 1 or self._pp > 1:
                raise ValueError(
                    "kv_cache_dtype=int8 does not compose with sp/pp meshes"
                )

        if params is None:
            params = self.module.init_params(cfg, jax.random.key(seed))
        pspecs = shardings.param_specs_for(params, pp=self._pp > 1)
        self.params = shardings.shard_tree(params, pspecs, self.mesh)
        self._kv_init_kw = {} if kvdt == "auto" else {"dtype": known[kvdt]}
        kp, vp = self.module.init_kv_pages(
            cfg, num_pages, page_size, **self._kv_init_kw
        )
        kv_sh = self._kv_sharding()
        self.k_pages = jax.device_put(kp, kv_sh)
        self.v_pages = jax.device_put(vp, kv_sh)
        self.k_scales = self.v_scales = None
        if self.kv_quant:
            from production_stack_tpu.ops.quant import init_kv_scales

            sc_sh = self._kv_scales_sharding()
            KH = getattr(cfg, "num_kv_heads", 1)
            # two independent buffers: both are donated every step, and a
            # shared device_put result would be one buffer donated twice
            self.k_scales = jax.device_put(
                init_kv_scales(cfg.num_layers, num_pages, KH), sc_sh
            )
            self.v_scales = jax.device_put(
                init_kv_scales(cfg.num_layers, num_pages, KH), sc_sh
            )
        self._rng = jax.random.key(seed)

        self.enable_lora = enable_lora
        self.max_loras = max_loras
        self.max_lora_rank = max_lora_rank
        self.lora_targets = tuple(lora_targets)
        self.lora = None
        if enable_lora:
            if not hasattr(self.module, "init_lora_buffers"):
                raise ValueError(
                    f"LoRA is not supported for model family "
                    f"{self.module.__name__.rsplit('.', 1)[-1]!r} (llama-family only)"
                )
            # slot-stacked adapter buffers, replicated (small; the deltas they
            # produce inherit the activations' sharding under GSPMD).
            # max_loras counts adapters; slot 0 is the base model, hence +1.
            buf = self.module.init_lora_buffers(
                cfg, max_loras + 1, max_lora_rank, self.lora_targets
            )
            rep = NamedSharding(self.mesh, P())
            self.lora = jax.tree.map(lambda x: jax.device_put(x, rep), buf)
            self._set_lora_fn = None  # built lazily in set_lora_slot

        self._row_sh = NamedSharding(self.mesh, shardings.BATCH_SPECS["input_ids"])
        self._vec_sh = NamedSharding(self.mesh, shardings.BATCH_SPECS["kv_lens"])
        # sampled tokens come back fully replicated so the leader process can
        # fetch the whole batch in multi-host serving (each process can only
        # address its own shards); logits/pools keep their compiler-chosen or
        # donated layouts.
        self._rep = NamedSharding(self.mesh, P())
        self._steps: dict[bool, Any] = {}  # want_logprobs -> jitted step
        self._set_page_fn = None  # built lazily in set_page
        self._get_page_fn = None  # built lazily in get_page (multi-host)
        self._get_pages_fns = {}  # batched offload spill, per id-count bucket
        self._set_pages_fns = {}  # batched offload restore
        self._last_hist = None    # device history after a burst (chaining)
        self._params_host = None  # host copy during sleep level 2
        self._encode = None       # built lazily in encode (pooled embeddings)
        self._multi_steps: dict[tuple, Any] = {}  # (k, want_lp) -> jitted decode
        self._spec_fns: dict[tuple, Any] = {}   # (steps, k, n) -> jitted spec decode

    def _stage(self, inp: StepInput, with_limits: bool = False) -> dict:
        """Host→device staging shared by step/step_multi: split the RNG and
        device_put every input with the runner's shardings."""
        self._rng, key = jax.random.split(self._rng)
        if self.mesh.devices.size == 1:
            # single chip: hand numpy straight to the jitted call — one
            # transfer batch instead of a device_put round trip per array
            # (matters on network-attached chips). Device arrays (burst
            # chaining feeds the previous burst's tokens back without a
            # host fetch) pass through untouched.
            row = vec = lambda x, dt: (
                x if isinstance(x, jax.Array) else np.asarray(x, np.dtype(dt))
            )
        else:
            row = lambda x, dt: jax.device_put(jnp.asarray(x, dt), self._row_sh)
            vec = lambda x, dt: jax.device_put(jnp.asarray(x, dt), self._vec_sh)
        lora_ids = None
        if self.lora is not None:
            ids_arr = (
                inp.lora_ids
                if inp.lora_ids is not None
                else np.zeros(np.asarray(inp.kv_lens).shape, np.int32)
            )
            lora_ids = vec(ids_arr, jnp.int32)
        staged = dict(
            input_ids=row(inp.input_ids, jnp.int32),
            positions=row(inp.positions, jnp.int32),
            page_table=row(inp.page_table, jnp.int32),
            kv_lens=vec(inp.kv_lens, jnp.int32),
            temperature=vec(inp.temperature, jnp.float32),
            top_k=vec(inp.top_k, jnp.int32),
            top_p=vec(inp.top_p, jnp.float32),
            key=key,
            lora_ids=lora_ids,
        )
        if with_limits:
            B = np.asarray(inp.kv_lens).shape[0]
            limits = (
                inp.kv_limits
                if inp.kv_limits is not None
                else np.full((B,), np.iinfo(np.int32).max // 2, np.int32)
            )
            staged["kv_limits"] = vec(limits, jnp.int32)
        if inp.history is not None and inp.presence is not None:
            staged["pen"] = (
                row(inp.history, jnp.int32),
                vec(inp.prompt_lens, jnp.int32),
                vec(inp.presence, jnp.float32),
                vec(inp.frequency, jnp.float32),
                vec(inp.repetition, jnp.float32),
            )
        if inp.bias_ids is not None:
            staged["bias"] = (
                row(inp.bias_ids, jnp.int32),
                row(inp.bias_vals, jnp.float32),
            )
        return staged

    def _note_program_variant(self, family: str, sig) -> None:
        """Flight-recorder marker at a jit-cache miss: a NEW program variant
        is about to trace + compile (the actual XLA compile seconds land via
        the jax.monitoring listener in engine/devicemon.py — this event ties
        them to WHICH serving shape caused the compile). Steady-state serving
        should record none of these; a stream of them mid-traffic means the
        shape bucketing regressed and the engine is retracing."""
        from production_stack_tpu.tracing import get_flightrecorder

        get_flightrecorder().record(
            "compile", event="program_variant", family=family, sig=repr(sig)
        )

    def _get_step(self, want_lp: bool, want_pen: bool):
        sig = (want_lp, want_pen)
        if sig not in self._steps:
            self._note_program_variant("step", sig)
            rep, n = self._rep, None
            outs = (rep, n, rep, rep, rep, n, n) if want_lp else (rep, n, n, n)
            donate = (1, 2)
            if self.kv_quant:
                outs = outs + (n, n)  # updated scales pools
                donate = (1, 2, 15)   # kv_scales tuple rides at arg 15
            self._steps[sig] = jax.jit(
                functools.partial(
                    _step_fn, self._forward, self.cfg, want_lp, want_pen
                ),
                donate_argnums=donate,
                out_shardings=outs,
            )
        return self._steps[sig]

    def step(self, inp: StepInput, want_logprobs: bool = False):
        """Run one forward+sample step. Returns (token_ids [B], logits [B, V])
        or, with ``want_logprobs``, (ids, logits, (chosen_lp [B],
        top_ids [B, K], top_lp [B, K]))."""
        s = self._stage(inp)
        want_pen = "pen" in s
        args = (
            self.params, self.k_pages, self.v_pages,
            s["input_ids"], s["positions"], s["page_table"], s["kv_lens"],
            s["temperature"], s["top_k"], s["top_p"], s["key"],
            self.lora, s["lora_ids"], s.get("pen"), s.get("bias"),
        )
        if self.kv_quant:
            args = args + ((self.k_scales, self.v_scales),)
        out = self._get_step(want_logprobs, want_pen)(*args)
        if self.kv_quant:
            *out, self.k_scales, self.v_scales = out
        if want_logprobs:
            ids, logits, lp, tids, tlp, self.k_pages, self.v_pages = out
            return ids, logits, (lp, tids, tlp)
        ids, logits, self.k_pages, self.v_pages = out
        return ids, logits

    def step_multi(self, inp: StepInput, k: int, want_logprobs: bool = False):
        """Run k fused decode steps in ONE device program (lax.scan feeding
        each sampled token back as the next input). Returns tokens [B, k] —
        or (tokens, (chosen_lp [B, k], top_ids [B, k, K], top_lp [B, k, K]))
        with ``want_logprobs``.

        Why: on serving hosts every dispatch pays host<->device latency (and
        per-call device_puts); at decode, compute per step is a few ms, so the
        round trip dominates. Fusing k steps amortizes it k-fold — the
        TPU-native answer to the reference's multi-step scheduling knob.
        Sequences that run out of budget mid-burst (EOS handling is host-side)
        are masked via ``kv_limits``: their positions go to -1, so KV writes
        drop and attention masks, and the host discards their surplus tokens.
        """
        if k == 1:
            if want_logprobs:
                ids, _, lps = self.step(inp, want_logprobs=True)
                lp, tids, tlp = lps
                return jnp.asarray(ids)[:, None], (
                    jnp.asarray(lp)[:, None],
                    jnp.asarray(tids)[:, None],
                    jnp.asarray(tlp)[:, None],
                )
            ids, _ = self.step(inp)
            return jnp.asarray(ids)[:, None]
        s = self._stage(inp, with_limits=True)
        want_pen = "pen" in s
        sig = (k, want_logprobs, want_pen)
        if sig not in self._multi_steps:
            self._note_program_variant("multi_step", sig)
            rep, n = self._rep, None
            outs = (
                (rep, rep, rep, rep, rep, n, n)
                if want_logprobs
                else (rep, rep, n, n)
            )
            fn = _multi_step_deferred_fn if self._kv_burst_ok else _multi_step_fn
            donate = (1, 2)
            if self.kv_quant:
                # int8 pools require the deferred-burst path (enforced at
                # construction): pools + scales stay scan constants, and the
                # single burst commit is the quantizer
                outs = outs + (n, n)
                donate = (1, 2, 16)
            self._multi_steps[sig] = jax.jit(
                functools.partial(
                    fn, self._forward, self.cfg, k,
                    want_logprobs, want_pen,
                ),
                donate_argnums=donate,
                out_shardings=outs,
            )
        args = (
            self.params, self.k_pages, self.v_pages,
            s["input_ids"], s["positions"], s["page_table"], s["kv_lens"],
            s["kv_limits"], s["temperature"], s["top_k"], s["top_p"], s["key"],
            self.lora, s["lora_ids"], s.get("pen"), s.get("bias"),
        )
        if self.kv_quant:
            args = args + ((self.k_scales, self.v_scales),)
        out = self._multi_steps[sig](*args)
        if self.kv_quant:
            *out, self.k_scales, self.v_scales = out
        if want_logprobs:
            toks, lp, tids, tlp, hist_f, self.k_pages, self.v_pages = out
            self._last_hist = hist_f if want_pen else None
            return toks, (lp, tids, tlp)
        toks, hist_f, self.k_pages, self.v_pages = out
        self._last_hist = hist_f if want_pen else None
        return toks

    def step_multi_pipelined(
        self,
        inp: StepInput,
        k: int,
        bursts: int,
        want_logprobs: bool = False,
        fetch_group: int = 0,
    ) -> list:
        """Dispatch ``bursts`` chained k-step decode bursts WITHOUT fetching
        between them; returns the per-burst device token arrays ([B, k] each)
        — or, with ``fetch_group`` g > 0 (and no logprobs), per-GROUP arrays
        ([B, <=g*k] each) whose on-device concatenation is enqueued right at
        the group boundary and whose host copy starts immediately.

        Why: on network-attached TPUs every host fetch costs a full round
        trip (~100 ms), comparable to the burst's compute. Chaining feeds
        burst j+1's input token straight from burst j's device-resident
        output (toks[:, -1:]), so a chain of m bursts costs m*compute + 1 RTT
        when the caller finally fetches, instead of m*(compute + RTT).
        Grouped fetching goes further: because device programs execute in
        ENQUEUE order, a group's concat+copy enqueued at its boundary
        completes as soon as ITS bursts do — the transfer overlaps the later
        bursts' compute, so the caller can apply/emit group j while group
        j+1 still runs (a concat enqueued after the last burst would wait
        for the whole chain instead).

        The host mirrors the device's per-row activity rule exactly
        (_multi_step_fn body: emit; active = pos>=0 & lens<kv_limits;
        pos = active ? pos+1 : -1; lens += active) to derive each burst's
        positions/kv_lens, and passes pos=-1 for rows that went inactive so
        the seam step's KV writes drop instead of corrupting the last real
        token's page slot. Requires inp.kv_limits sized for the FULL
        bursts*k budget (scheduler plans this).
        """
        if bursts <= 1:
            res = self.step_multi(inp, k, want_logprobs)
            if fetch_group and not want_logprobs:
                res.copy_to_host_async()
            return [res]
        pos = np.asarray(inp.positions, np.int64)[:, 0].copy()
        lens = np.asarray(inp.kv_lens, np.int64).copy()
        limits = np.asarray(inp.kv_limits, np.int64)
        outs = []
        group: list = []

        def flush_group():
            if not group:
                return
            cat = group[0] if len(group) == 1 else jnp.concatenate(group, axis=1)
            cat.copy_to_host_async()
            outs.append(cat)
            group.clear()

        cur = inp
        for j in range(bursts):
            res = self.step_multi(cur, k, want_logprobs)
            toks = res[0] if want_logprobs else res
            if fetch_group and not want_logprobs:
                group.append(res)
                if len(group) >= fetch_group:
                    flush_group()
            else:
                outs.append(res)
            if j == bursts - 1:
                break
            for _ in range(k):  # exact mirror of the device scan
                active = (pos >= 0) & (lens < limits)
                pos = np.where(active, pos + 1, -1)
                lens = lens + active
            cur = dataclasses.replace(
                inp,
                input_ids=toks[:, -1:],
                positions=pos[:, None].astype(np.int32),
                kv_lens=lens.astype(np.int32),
                # penalties: the DEVICE history (with this burst's tokens
                # already recorded) feeds the next burst — the host copy
                # staged at chain start is stale past the seam
                history=(
                    self._last_hist if inp.history is not None else None
                ),
            )
        if fetch_group and not want_logprobs:
            flush_group()
        return outs

    def step_spec(
        self, inp: StepInput, history: Any, steps: int, spec_k: int, ngram: int
    ) -> jnp.ndarray:
        """Fused speculative decode: ``steps`` rounds of (n-gram draft →
        parallel verify → rejection-sample accept) in ONE device program.

        The draft model is prompt-lookup (vLLM's ngram speculator, TPU-native):
        the trailing ``ngram`` tokens are matched against the sequence's own
        token history *on device*, and the ``spec_k`` tokens that followed the
        most recent match become the draft. One forward over 1+spec_k
        positions scores them all; a sampled target token per position gives
        exact rejection-sampling acceptance (for a deterministic draft,
        "sample t ~ p, accept iff t == draft" IS the spec-sampling rule, and
        the first mismatching t is the correction token). Each round emits
        1..spec_k+1 tokens for one forward pass — decode becomes MXU-bound
        verify work instead of latency-bound single-token steps.

        Args:
          inp: decode-shaped StepInput ([B, 1] inputs; kv_limits REQUIRED —
               a row stays active while ``lens + spec_k <= kv_limits``).
          history: [B, H] int32 token ids (prompt + output so far), 0-padded.
        Returns tokens [B, steps, 1+spec_k] int32, -1 where nothing emitted.
        """
        if self.kv_quant:
            raise ValueError(
                "speculative decoding is not supported with "
                "kv_cache_dtype=int8 (the spec scan carries raw pool blocks)"
            )
        sig = (steps, spec_k, ngram)
        if sig not in self._spec_fns:
            self._note_program_variant("spec_step", sig)
            self._spec_fns[sig] = jax.jit(
                functools.partial(
                    _spec_fn, self._forward, self.cfg, steps, spec_k, ngram
                ),
                donate_argnums=(1, 2),
                out_shardings=(self._rep, None, None),
            )
        s = self._stage(inp, with_limits=True)
        hist = jax.device_put(jnp.asarray(history, jnp.int32), self._row_sh) \
            if self.mesh.devices.size > 1 else np.asarray(history, np.int32)
        toks, self.k_pages, self.v_pages = self._spec_fns[sig](
            self.params,
            self.k_pages,
            self.v_pages,
            hist,
            s["input_ids"],
            s["positions"],
            s["page_table"],
            s["kv_lens"],
            s["kv_limits"],
            s["temperature"],
            s["top_k"],
            s["top_p"],
            s["key"],
            self.lora,
            s["lora_ids"],
        )
        return toks

    def encode(self, input_ids, positions) -> jnp.ndarray:
        """Pooled-embedding forward ([B, T] -> [B, H] unit vectors). Shapes
        must arrive bucketed (engine quantizes B and T)."""
        if self._encode is None:
            if not hasattr(self.module, "encode"):
                raise ValueError(
                    f"embeddings are not supported for model family "
                    f"{self.module.__name__.rsplit('.', 1)[-1]!r}"
                )
            self._encode = jax.jit(
                functools.partial(self.module.encode, cfg=self.cfg),
                out_shardings=self._rep,
            )
        row = lambda x: jax.device_put(jnp.asarray(x, jnp.int32), self._row_sh)
        return self._encode(
            params=self.params, input_ids=row(input_ids), positions=row(positions)
        )

    # -- LoRA slot management (engine/lora.py drives these) ------------------

    def set_lora_slot(self, slot: int, tensors: dict, scale: float) -> None:
        """Write one adapter's stacked weights into `slot` in place."""
        if self.lora is None:
            raise RuntimeError("runner built with enable_lora=False")
        if not 0 < slot <= self.max_loras:
            raise ValueError(f"slot must be in [1, {self.max_loras}], got {slot}")
        if self._set_lora_fn is None:
            def _set(layers, scale_vec, slot, new_layers, new_scale):
                layers = {
                    k: (v.at[:, slot].set(new_layers[k].astype(v.dtype))
                        if k in new_layers else v)
                    for k, v in layers.items()
                }
                return layers, scale_vec.at[slot].set(new_scale)

            self._set_lora_fn = jax.jit(_set, donate_argnums=(0, 1))
        self.lora["layers"], self.lora["scale"] = self._set_lora_fn(
            self.lora["layers"], self.lora["scale"], jnp.int32(slot),
            {k: jnp.asarray(v) for k, v in tensors.items()},
            jnp.float32(scale),
        )

    def clear_lora_slot(self, slot: int) -> None:
        if self.lora is None:
            raise RuntimeError("runner built with enable_lora=False")
        # per-slot leaf shape: [L, S, d1, d2] -> [L, d1, d2]
        zeros = {
            k: np.zeros((v.shape[0],) + v.shape[2:], np.float32)
            for k, v in self.lora["layers"].items()
        }
        self.set_lora_slot(slot, zeros, 0.0)

    def get_page(self, pid: int):
        """Fetch one page's K/V to host ([L, page_size, KH, D] each).

        Multi-host: a process can only address its own pool shards, so the
        page is first laid out fully-replicated by an SPMD program (the
        all-gather rides ICI/DCN) and the LOCAL replica is fetched. This is
        a REPLICATED dispatch (distributed.py) — every process runs the same
        program, the leader's host fetch sees the whole page — which is what
        makes KV offload tiers work under multi-host serving (the reference
        runs LMCache under multi-node vLLM the same leader-driven way,
        deployment-vllm-multi.yaml:202-331)."""
        if not self.k_pages.is_fully_addressable:
            if self._get_page_fn is None:
                rep = NamedSharding(self.mesh, P())
                self._get_page_fn = jax.jit(
                    lambda kp, vp, i: (kp[:, i], vp[:, i]),
                    out_shardings=(rep, rep),
                )
            k, v = self._get_page_fn(self.k_pages, self.v_pages, jnp.int32(pid))
            return jax.device_get((k, v))
        return jax.device_get((self.k_pages[:, pid], self.v_pages[:, pid]))

    def get_pages(self, pids: "list[int]"):
        """Fetch N pages' K/V in ONE host round trip.

        The per-page :meth:`get_page` costs a full host<->device round trip
        (~100 ms on a network-attached chip); an eviction storm spilling a
        long history page-by-page would stall the engine loop for seconds.
        The page-id vector is bucketed to powers of two (padded by repeating
        the last id — an extra gather lane, harmless) so the program count
        stays bounded. Returns ``(ks, vs)``: per-page ``[L, page, KH, D]``
        host arrays."""
        n = len(pids)
        if n == 0:
            # REPLICATED multi-host dispatch surface: an unguarded empty call
            # would raise (pids[-1]) on whichever process hit it and desync
            # the follower set — return without touching the device
            return [], []
        bucket = 1
        while bucket < n:
            bucket <<= 1
        ids = jnp.asarray(
            np.asarray(list(pids) + [pids[-1]] * (bucket - n), np.int32)
        )
        fn = self._get_pages_fns.get(bucket)
        if fn is None:
            rep = NamedSharding(self.mesh, P())
            fn = jax.jit(
                lambda kp, vp, i: (kp[:, i], vp[:, i]),
                out_shardings=(rep, rep),
            )
            self._get_pages_fns[bucket] = fn
        k, v = jax.device_get(fn(self.k_pages, self.v_pages, ids))
        return [k[:, i] for i in range(n)], [v[:, i] for i in range(n)]

    def set_pages(self, pids: "list[int]", ks, vs) -> None:
        """Write N pages in ONE host->device upload + one scatter program
        (batched offload restore — see :meth:`get_pages` for why). ``ks``/
        ``vs`` are per-page ``[L, page, KH, D]`` arrays. Padding duplicates
        the last (id, data) lane, so the duplicate scatter rewrites the same
        value — deterministic."""
        n = len(pids)
        if n == 0:
            return  # see get_pages: empty calls must be no-ops, not errors
        bucket = 1
        while bucket < n:
            bucket <<= 1
        ids = np.asarray(list(pids) + [pids[-1]] * (bucket - n), np.int32)
        dt = self.k_pages.dtype
        k = np.stack(list(ks) + [ks[-1]] * (bucket - n), axis=1)
        v = np.stack(list(vs) + [vs[-1]] * (bucket - n), axis=1)
        fn = self._set_pages_fns.get(bucket)
        if fn is None:
            fn = jax.jit(
                lambda kp, vp, i, k, v: (
                    kp.at[:, i].set(k), vp.at[:, i].set(v)
                ),
                donate_argnums=(0, 1),
            )
            self._set_pages_fns[bucket] = fn
        rep = self._rep
        kd = jax.device_put(jnp.asarray(k, dt), rep)
        vd = jax.device_put(jnp.asarray(v, dt), rep)
        self.k_pages, self.v_pages = fn(
            self.k_pages, self.v_pages, jnp.asarray(ids), kd, vd
        )

    # -- quantized pools: the serde boundary moves int8 pages + scales -------
    # (KVOffloadConnector detects runner.kv_quant and uses these so blobs
    # ship the halved int8 byte stream end-to-end — ops/quant.py contract)

    def get_pages_quant(self, pids: "list[int]"):
        """Fetch N quantized pages + their scales in ONE host round trip.
        Returns (ks, vs, sks, svs): per-page ``[L, page, KH, D]`` int8 and
        ``[L, KH]`` f32 host arrays — the exact pool bytes, no dequant."""
        n = len(pids)
        if n == 0:
            return [], [], [], []
        bucket = 1
        while bucket < n:
            bucket <<= 1
        ids = jnp.asarray(
            np.asarray(list(pids) + [pids[-1]] * (bucket - n), np.int32)
        )
        fn = self._get_pages_fns.get(("q", bucket))
        if fn is None:
            rep = NamedSharding(self.mesh, P())
            fn = jax.jit(
                lambda kp, vp, ks, vs, i: (
                    kp[:, i], vp[:, i], ks[:, i], vs[:, i]
                ),
                out_shardings=(rep, rep, rep, rep),
            )
            self._get_pages_fns[("q", bucket)] = fn
        k, v, sk, sv = jax.device_get(
            fn(self.k_pages, self.v_pages, self.k_scales, self.v_scales, ids)
        )
        return (
            [k[:, i] for i in range(n)], [v[:, i] for i in range(n)],
            [sk[:, i] for i in range(n)], [sv[:, i] for i in range(n)],
        )

    def set_pages_quant(self, pids: "list[int]", ks, vs, sks, svs) -> None:
        """Write N quantized pages + scales in ONE upload + scatter (the
        restore twin of :meth:`get_pages_quant`).

        Validates the scales before touching the pools: transferred pages
        (disagg fabric frames, migration ships) arrive from another engine,
        and an int8 page scattered with missing or misshaped scales would
        dequantize to garbage silently — reject loudly instead so the
        transfer path takes its tier/recompute fallback."""
        n = len(pids)
        if n == 0:
            return
        ks, vs, sks, svs = list(ks), list(vs), list(sks), list(svs)
        if not (len(ks) == len(vs) == len(sks) == len(svs) == n):
            raise ValueError(
                f"set_pages_quant: {n} pids but "
                f"{len(ks)}/{len(vs)}/{len(sks)}/{len(svs)} pages/scales"
            )
        scale_shape = (self.k_scales.shape[0], self.k_scales.shape[2])
        for sk_i, sv_i in zip(sks, svs):
            for s in (sk_i, sv_i):
                a = np.asarray(s)
                if a.shape != scale_shape or not np.issubdtype(
                    a.dtype, np.floating
                ):
                    raise ValueError(
                        f"set_pages_quant: scale {a.shape}/{a.dtype} does "
                        f"not match pool scales {scale_shape}/float32 — a "
                        "quantized page arrived without usable per-kv-head "
                        "scales"
                    )
        bucket = 1
        while bucket < n:
            bucket <<= 1
        pad = bucket - n
        ids = np.asarray(list(pids) + [pids[-1]] * pad, np.int32)
        k = np.stack(list(ks) + [ks[-1]] * pad, axis=1)
        v = np.stack(list(vs) + [vs[-1]] * pad, axis=1)
        sk = np.stack(list(sks) + [sks[-1]] * pad, axis=1)
        sv = np.stack(list(svs) + [svs[-1]] * pad, axis=1)
        fn = self._set_pages_fns.get(("q", bucket))
        if fn is None:
            fn = jax.jit(
                lambda kp, vp, ksc, vsc, i, k, v, sk, sv: (
                    kp.at[:, i].set(k), vp.at[:, i].set(v),
                    ksc.at[:, i].set(sk), vsc.at[:, i].set(sv),
                ),
                donate_argnums=(0, 1, 2, 3),
            )
            self._set_pages_fns[("q", bucket)] = fn
        rep = self._rep
        put = lambda x, dt: jax.device_put(jnp.asarray(x, dt), rep)
        self.k_pages, self.v_pages, self.k_scales, self.v_scales = fn(
            self.k_pages, self.v_pages, self.k_scales, self.v_scales,
            jnp.asarray(ids),
            put(k, jnp.int8), put(v, jnp.int8),
            put(sk, jnp.float32), put(sv, jnp.float32),
        )

    def get_page_device(self, pid: int):
        """One page's K/V as SINGLE-DEVICE arrays (device 0), for the
        device-to-device transfer path: the pool may be kv-head-sharded over
        tp, but the XLA transfer service pulls whole single-shard buffers —
        the gather rides ICI, never the host."""
        sh = jax.sharding.SingleDeviceSharding(self.mesh.devices.flat[0])
        return (
            jax.device_put(self.k_pages[:, pid], sh),
            jax.device_put(self.v_pages[:, pid], sh),
        )

    def set_page(self, pid: int, k, v) -> None:
        """Write one page's K/V into the pools in place (offload restore /
        disaggregated-prefill KV injection). Accepts host arrays or device
        arrays from another mesh/device (device-to-device transfer staging) —
        those reshard onto this runner's mesh first, device-side."""
        if self._set_page_fn is None:
            self._set_page_fn = jax.jit(
                lambda kp, vp, i, k, v: (kp.at[:, i].set(k), vp.at[:, i].set(v)),
                donate_argnums=(0, 1),
            )
        dt = self.k_pages.dtype
        rep = self._rep  # replicated over this runner's mesh
        k = jax.device_put(jnp.asarray(k, dt), rep)
        v = jax.device_put(jnp.asarray(v, dt), rep)
        self.k_pages, self.v_pages = self._set_page_fn(
            self.k_pages, self.v_pages, jnp.int32(pid), k, v,
        )

    # -- multi-host device-to-device KV (disaggregated prefill over DCN) ------
    # Every method here is REPLICATED (distributed.py): the leader broadcasts
    # it over the step stream and each process acts on ITS shard/copy, so KV
    # bytes move device->device over the XLA transfer service — never through
    # the host or the (host-byte) step stream. Reference analogue: NIXL
    # GPU-direct between prefill and decode pods
    # (/root/reference helm/templates/deployment-vllm-multi.yaml:256-296).

    def _local_mesh_devices(self) -> list:
        return [
            d for d in self.mesh.devices.flat
            if d.process_index == jax.process_index()
        ]

    def _replicate_page(self, pid: int):
        """SPMD program laying one page out fully-replicated (the all-gather
        rides ICI/DCN); every process ends up with the whole page on each of
        its local devices."""
        if self._get_page_fn is None:
            rep = NamedSharding(self.mesh, P())
            self._get_page_fn = jax.jit(
                lambda kp, vp, i: (kp[:, i], vp[:, i]),
                out_shardings=(rep, rep),
            )
        return self._get_page_fn(self.k_pages, self.v_pages, jnp.int32(pid))

    def kv_endpoint_start(self) -> None:
        """Start this process's transfer-service endpoint and publish its
        address through the JAX coordination KV store (the same trust domain
        as the step-sync secret, distributed.py:resolve_sync_secret)."""
        if getattr(self, "kv_endpoint", None) is not None:
            return
        from production_stack_tpu.kvoffload.transfer import DeviceKVEndpoint

        # bind/advertise host is per-process (each pod has its own IP):
        # PSTPU_KV_EP_HOST is set per pod (fieldRef status.podIP in the
        # helm chart); loopback covers single-machine tests
        import os as os_mod

        host = (
            os_mod.environ.get("PSTPU_KV_EP_HOST")
            or getattr(self, "kv_endpoint_host", None)
            or "127.0.0.1"
        )
        self.kv_endpoint = DeviceKVEndpoint(self, host=host)
        self.kv_staged: dict[str, tuple] = {}
        try:
            from jax._src import distributed as jdist

            client = jdist.global_state.client
            if client is not None:
                client.key_value_set(
                    f"pstpu/kv_ep/{jax.process_index()}",
                    self.kv_endpoint.address,
                )
        except Exception:  # noqa: BLE001 - single-process: no coordination svc
            pass

    def kv_offer_page(self, pid: int, uuid_base: int, pullers: int) -> tuple:
        """Replicate one page, then offer this process's local copy for every
        consumer process assigned to it: consumer c pulls from producer
        c % P under uuid ``uuid_base + c``, so process i offers exactly
        {uuid_base + c : c % P == i}. Returns (shape, dtype) from the local
        copy (the leader's caller needs them for page_ready)."""
        self.kv_endpoint_start()
        k, v = self._replicate_page(pid)
        k_l = k.addressable_shards[0].data
        v_l = v.addressable_shards[0].data
        i, nproc = jax.process_index(), jax.process_count()
        for c in range(i, int(pullers), nproc):
            self.kv_endpoint.offer_fixed(int(uuid_base) + c, k_l, v_l)
        return list(k_l.shape), str(k_l.dtype)

    def kv_pull_page(
        self, assignments: list, shape, dtype, key: str
    ) -> int:
        """Pull this process's copy of a page from its assigned producer
        endpoint and stage it locally; returns the staged byte count (0 on
        failure — the leader's staging accounting needs the real size even
        when its budget reservation TTL'd out mid-pull). ``assignments`` has
        one (addr, uuid) per consumer process. A pull failure stages nothing
        but does NOT raise — the leader notices its own failure (or a later
        restore mismatch) and replicates kv_unstage_page so every process
        converges, then the producer falls back to TCP blobs for the page."""
        self.kv_endpoint_start()
        addr, uuid = assignments[jax.process_index() % len(assignments)]
        self._kv_staged_sweep()
        try:
            k_l, v_l = self.kv_endpoint.pull(addr, int(uuid), shape, dtype)
        except Exception as e:  # noqa: BLE001
            import logging

            logging.getLogger(__name__).warning("device kv pull failed: %s", e)
            return 0
        import time as time_mod

        # TTL is 2x the leader-side DeviceStaging ttl: the leader must always
        # give up on a page (and replicate kv_unstage_page) before any
        # follower's local sweep could drop it — else a leader restore would
        # find follower staging gone (fatal desync by design)
        self.kv_staged[key] = (k_l, v_l, time_mod.monotonic() + 240.0)
        return int(k_l.nbytes) * 2

    def kv_restore_page(self, key: str, pid: int) -> None:
        """Write a staged page into this process's pool shards. The device
        program is identical on every process (SPMD set_page); the staged
        copy is local, so no bytes cross the step stream. Missing staged
        state here is a desync bug — fatal by design (distributed.py
        failure model)."""
        entry = self.kv_staged.pop(key, None)
        if entry is None:
            raise RuntimeError(
                f"kv_restore_page: page {key!r} not staged on process "
                f"{jax.process_index()} — staging diverged from the leader"
            )
        k_l, v_l, _ = entry
        if self._set_page_fn is None:
            self._set_page_fn = jax.jit(
                lambda kp, vp, i, k, v: (kp.at[:, i].set(k), vp.at[:, i].set(v)),
                donate_argnums=(0, 1),
            )
        dt = self.k_pages.dtype
        k_l = jnp.asarray(k_l, dt)
        v_l = jnp.asarray(v_l, dt)
        if self.k_pages.is_fully_addressable:
            k_rep = jax.device_put(k_l, self._rep)
            v_rep = jax.device_put(v_l, self._rep)
        else:
            # assemble the replicated global operand from per-process local
            # copies: one single-device copy per local mesh device
            local = self._local_mesh_devices()
            k_rep = jax.make_array_from_single_device_arrays(
                k_l.shape, self._rep,
                [jax.device_put(k_l, d) for d in local],
            )
            v_rep = jax.make_array_from_single_device_arrays(
                v_l.shape, self._rep,
                [jax.device_put(v_l, d) for d in local],
            )
        self.k_pages, self.v_pages = self._set_page_fn(
            self.k_pages, self.v_pages, jnp.int32(pid), k_rep, v_rep,
        )

    def kv_unstage_page(self, key: str) -> None:
        """Drop a staged page on every process (leader-side staging expiry or
        a failed/partial pull). Host-side only — always symmetric-safe."""
        self.kv_staged.pop(key, None)

    def _kv_staged_sweep(self) -> None:
        """TTL cleanup for never-restored staged pages. Host-side dict work:
        divergent timing across processes cannot desync device state (the
        authoritative drop is the leader's replicated kv_unstage_page; this
        sweep only bounds worst-case device memory if that never arrives)."""
        import time as time_mod

        now = time_mod.monotonic()
        for k in [k for k, (_, _, d) in self.kv_staged.items() if d < now]:
            self.kv_staged.pop(k, None)

    def kv_pool_shard_layout(self) -> "list[tuple[str, int]]":
        """Static per-device KV pool footprint: ``(device_label, bytes)`` for
        every mesh device, k+v pools together.

        Computed from the pool SHARDING (shard_shape), not the live buffers —
        the live arrays are donated into every step, and a scrape racing the
        device thread would intermittently see a deleted buffer. With kv
        heads sharded over tp each chip holds ``total / (tp * pp)`` bytes
        (the per-chip pool the multichip serving path is sized by:
        docs/multichip-serving.md); a GQA pool that cannot split (KH % tp
        != 0) reports the full replicated footprint per device."""
        KH = getattr(self.cfg, "num_kv_heads", 1)
        shape = (
            self.cfg.num_layers, self.num_pages, self.page_size,
            KH, self.cfg.head_dim,
        )
        sh = self._kv_sharding()
        per = 2 * int(np.prod(sh.shard_shape(shape)))
        per *= np.dtype(self.kv_pool_dtype).itemsize  # 1 under int8
        if self.kv_quant:
            ssh = self._kv_scales_sharding()
            per += 2 * 4 * int(
                np.prod(ssh.shard_shape((self.cfg.num_layers, self.num_pages, KH)))
            )
        return [
            (f"{d.platform}:{d.id}", per) for d in self.mesh.devices.flat
        ]

    def _kv_sharding(self) -> NamedSharding:
        """Pool sharding for this mesh (pp shards the layer axis).

        KV heads shard over tp only when they divide evenly; a GQA model with
        fewer KV heads than the tp axis (e.g. 2 KV heads at tp=4) replicates
        the pool instead — the XLA attention path then reads it GSPMD-style
        (this is also why attn_impl=auto refuses pallas there)."""
        spec = shardings.KV_PAGES_SPEC_PP if self._pp > 1 else shardings.KV_PAGES_SPEC
        tp = dict(self.mesh.shape).get("tp", 1)
        if getattr(self.cfg, "num_kv_heads", 1) % tp:
            spec = P(*[None if ax == "tp" else ax for ax in spec])
        return NamedSharding(self.mesh, spec)

    def _kv_scales_sharding(self) -> NamedSharding:
        """Scales-pool sharding [L, P, KH]: the pool spec minus its
        page-slot and head-dim axes — the KH axis shards over tp exactly
        like the pages', so each chip holds its head-shard's scales."""
        spec = self._kv_sharding().spec
        return NamedSharding(self.mesh, P(spec[0], spec[1], spec[3]))

    def drop_kv_pools(self) -> None:
        """Release the KV pools' device memory (sleep level 1+)."""
        self.k_pages = None
        self.v_pages = None
        self.k_scales = None
        self.v_scales = None

    def offload_params(self) -> None:
        """Move params to host RAM (sleep level 2). Each process fetches its
        own addressable shards, so this works on multi-host meshes as a
        REPLICATED dispatch — vLLM's sleep level 2 equivalent, per process.

        Shards replicated across local devices (dp/sp axes, or wholly
        replicated leaves) are fetched and stored ONCE, keyed by shard
        index — saving host RAM is the entire point of level 2."""
        def off(arr):
            bufs: dict = {}
            placements = []
            for s in arr.addressable_shards:
                key = repr(s.index)
                if key not in bufs:
                    bufs[key] = np.asarray(s.data)
                placements.append((s.device, key))
            return (arr.shape, arr.sharding, placements, bufs)

        # build the full host tree BEFORE dropping the device refs: a
        # mid-tree failure (host OOM is the at-risk case) must leave the
        # engine wakeable with its device params intact
        host = jax.tree.map(off, self.params)
        self._params_host = host
        self.params = None

    def restore_params(self) -> None:
        """Re-materialize params on device from the per-process host shards
        saved by offload_params (sleep level 2 wake)."""
        if self._params_host is None:
            return  # offload never completed; device params are still live

        def back(saved):
            shape, sharding, placements, bufs = saved
            locals_ = [
                jax.device_put(bufs[key], dev) for dev, key in placements
            ]
            return jax.make_array_from_single_device_arrays(
                shape, sharding, locals_
            )

        self.params = jax.tree.map(
            back, self._params_host, is_leaf=lambda x: isinstance(x, tuple)
        )
        self._params_host = None

    def reset_kv(self) -> None:
        """Zero the page pools (sleep/wake support frees and re-creates them)."""
        kp, vp = self.module.init_kv_pages(
            self.cfg, self.num_pages, self.page_size, **self._kv_init_kw
        )
        kv_sh = self._kv_sharding()
        self.k_pages = jax.device_put(kp, kv_sh)
        self.v_pages = jax.device_put(vp, kv_sh)
        if self.kv_quant:
            from production_stack_tpu.ops.quant import init_kv_scales

            KH = getattr(self.cfg, "num_kv_heads", 1)
            sc_sh = self._kv_scales_sharding()
            self.k_scales = jax.device_put(
                init_kv_scales(self.cfg.num_layers, self.num_pages, KH), sc_sh
            )
            self.v_scales = jax.device_put(
                init_kv_scales(self.cfg.num_layers, self.num_pages, KH), sc_sh
            )


def _multi_step_fn(forward, cfg, k, want_lp, want_pen, params, k_pages,
                   v_pages, input_ids, positions, page_table, kv_lens,
                   kv_limits, temperature, top_k, top_p, key, lora=None,
                   lora_ids=None, pen=None, bias=None):
    """k fused decode steps; see ModelRunner.step_multi. input_ids/positions
    are [B, 1] (decode shape).

    The scan carries only the batch's gathered KV block, NOT the whole pool:
    XLA double-buffers while-loop carries, so carrying a multi-GB pool through
    the scan 2-3x's KV memory and OOMs real chips. The block is a local pool
    of B*P pages indexed by an identity page table, so ``forward`` is reused
    unchanged; pages the burst wrote are scattered back afterwards."""
    B, P = page_table.shape
    pool_pages = k_pages.shape[1]
    page_size = k_pages.shape[2]
    flat = page_table.reshape(-1)
    k_blk = jnp.take(k_pages, flat, axis=1)  # [L, B*P, page, KH, D]
    v_blk = jnp.take(v_pages, flat, axis=1)
    local_pt = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P)
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    keys = jax.random.split(key, k)
    if want_pen:
        hist0, plens, pres, freq, rep = pen
        H = hist0.shape[1]
        rows = jnp.arange(hist0.shape[0], dtype=jnp.int32)
    else:
        hist0 = jnp.zeros((input_ids.shape[0], 1), jnp.int32)  # inert carry

    def body(carry, key_i):
        ids, pos, lens, kp, vp, hist = carry
        logits, kp, vp = forward(
            params, cfg, ids, pos, kp, vp, local_pt, lens, **kw
        )
        sample_from = logits
        if want_pen:
            sample_from = apply_penalties(
                logits.astype(jnp.float32), hist, lens, plens, pres, freq, rep
            )
        if bias is not None:
            sample_from = apply_logit_bias(
                sample_from.astype(jnp.float32), *bias
            )
        if want_lp:
            nxt, lp, tids, tlp = sample_with_logprobs(
                logits, key_i, temperature, top_k, top_p,
                sample_from=sample_from,
            )
            emit = (nxt, lp, tids, tlp)
        else:
            nxt = sample(sample_from, key_i, temperature, top_k, top_p)  # [B]
            emit = nxt
        if want_pen:
            # record this step's token at its absolute position so later
            # steps in the burst count it
            slot = jnp.where(pos[:, 0] >= 0, lens, H)
            hist = hist.at[rows, slot].set(nxt, mode="drop")
        # a row continues while it was active this step and has budget left
        active = (pos[:, 0] >= 0) & (lens < kv_limits)
        pos = jnp.where(active, pos[:, 0] + 1, -1)[:, None]
        lens = lens + active.astype(lens.dtype)
        ids = jnp.where(active, nxt, 0)[:, None]
        return (ids, pos, lens, kp, vp, hist), emit

    (_, _, lens_f, k_blk, v_blk, hist_f), emitted = jax.lax.scan(
        body, (input_ids, positions, kv_lens, k_blk, v_blk, hist0), keys
    )
    toks = emitted[0] if want_lp else emitted
    # scatter back only the logical pages the burst wrote
    # ([(lens0-1)//page, (lens_f-1)//page] per row): those are uniquely owned
    # by each row, so no duplicate indices; everything else in the block is an
    # unmodified copy (incl. shared prefix pages and padding), dropped via an
    # out-of-range index.
    p_idx = jnp.arange(P, dtype=jnp.int32)[None, :]
    first = (kv_lens - 1) // page_size
    last = (lens_f - 1) // page_size
    written = (p_idx >= first[:, None]) & (p_idx <= last[:, None])
    safe = jnp.where(written, page_table, pool_pages).reshape(-1)
    k_pages = k_pages.at[:, safe].set(k_blk, mode="drop")
    v_pages = v_pages.at[:, safe].set(v_blk, mode="drop")
    # hist_f returns so chained bursts can feed it forward device-side
    # (penalty counts must include THIS burst's tokens at the next seam)
    if want_lp:
        _, lp, tids, tlp = emitted  # [k, B], [k, B, K]
        return (toks.T, lp.T, jnp.swapaxes(tids, 0, 1),
                jnp.swapaxes(tlp, 0, 1), hist_f, k_pages, v_pages)
    return toks.T, hist_f, k_pages, v_pages  # [B, k]


def _multi_step_deferred_fn(forward, cfg, k, want_lp, want_pen, params,
                            k_pages, v_pages, input_ids, positions,
                            page_table, kv_lens, kv_limits, temperature,
                            top_k, top_p, key, lora=None, lora_ids=None,
                            pen=None, bias=None, kv_scales=None):
    """k fused decode steps with DEFERRED KV scatters (kv_burst mode).

    The classic _multi_step_fn gathers the batch's pages into a local block
    and carries it through the scan; every step's in-place write forces XLA
    to materialize block-sized copies (the dominant cost of a decode step on
    v5e — the pools/blocks are ~0.5 GB while the new KV per step is ~0.5 MB).
    Here the pools are scan CONSTANTS (read-only), each step appends its
    K/V to a tiny [L, B, k, KH, D] window that attention folds in via the
    kernel's masked multi-token k_cur, and ONE batched scatter commits the
    whole burst afterwards."""
    B = input_ids.shape[0]
    L, _, page_size, KH, D = k_pages.shape
    C = k
    quant = kv_scales is not None
    # int8 pools: the burst window holds the quantizer's fp INPUT (committed
    # once, below); only the read path touches int8
    acc_dt = cfg.dtype if quant else k_pages.dtype
    k_acc = jnp.zeros((L, B, C, KH, D), acc_dt)
    v_acc = jnp.zeros((L, B, C, KH, D), acc_dt)
    counts = jnp.zeros((B,), jnp.int32)
    pos0 = positions[:, 0]
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    if quant:
        kw["kv_scales"] = kv_scales
    keys = jax.random.split(key, k)
    if want_pen:
        hist0, plens, pres, freq, rep = pen
        H = hist0.shape[1]
        rows = jnp.arange(hist0.shape[0], dtype=jnp.int32)
    else:
        hist0 = jnp.zeros((B, 1), jnp.int32)  # inert carry

    def body(carry, key_i):
        ids, pos, lens, counts, ka, va, hist = carry
        logits, ka_new, va_new = forward(
            params, cfg, ids, pos, k_pages, v_pages, page_table, lens,
            kv_burst=(ka, va, counts), **kw
        )
        sample_from = logits
        if want_pen:
            sample_from = apply_penalties(
                logits.astype(jnp.float32), hist, lens, plens, pres, freq, rep
            )
        if bias is not None:
            sample_from = apply_logit_bias(
                sample_from.astype(jnp.float32), *bias
            )
        if want_lp:
            nxt, lp, tids, tlp = sample_with_logprobs(
                logits, key_i, temperature, top_k, top_p,
                sample_from=sample_from,
            )
            emit = (nxt, lp, tids, tlp)
        else:
            nxt = sample(sample_from, key_i, temperature, top_k, top_p)  # [B]
            emit = nxt
        if want_pen:
            slot = jnp.where(pos[:, 0] >= 0, lens, H)
            hist = hist.at[rows, slot].set(nxt, mode="drop")
        # adopt the appended window entry only for rows active this step —
        # an inactive row's slot write was garbage and must not stick
        act_now = pos[:, 0] >= 0
        sel = act_now[None, :, None, None, None]
        ka = jnp.where(sel, ka_new, ka)
        va = jnp.where(sel, va_new, va)
        counts = counts + act_now.astype(counts.dtype)
        active = act_now & (lens < kv_limits)
        pos = jnp.where(active, pos[:, 0] + 1, -1)[:, None]
        lens = lens + active.astype(lens.dtype)
        ids = jnp.where(active, nxt, 0)[:, None]
        return (ids, pos, lens, counts, ka, va, hist), emit

    (_, _, _, counts_f, k_acc, v_acc, hist_f), emitted = jax.lax.scan(
        body, (input_ids, positions, kv_lens, counts, k_acc, v_acc, hist0),
        keys,
    )
    toks = emitted[0] if want_lp else emitted
    # one commit for the whole burst: window entry j of row b holds the
    # token at absolute position pos0 + j (valid for j < counts_f)
    jj = jnp.arange(C, dtype=jnp.int32)[None, :]
    commit_pos = jnp.where(
        (jj < counts_f[:, None]) & (pos0[:, None] >= 0),
        pos0[:, None] + jj,
        -1,
    )
    if quant:
        # the decode feedback write IS the quantizer (ops/quant.py): fresh
        # pages reset their scale, mid-page appends grow it and re-quantize
        from production_stack_tpu.ops.quant import (
            write_kv_pages_all_layers_quant,
        )

        k_scales, v_scales = kv_scales
        k_pages, v_pages, k_scales, v_scales = write_kv_pages_all_layers_quant(
            k_pages, v_pages, k_scales, v_scales, k_acc, v_acc,
            page_table, commit_pos,
        )
        if want_lp:
            _, lp, tids, tlp = emitted
            return (toks.T, lp.T, jnp.swapaxes(tids, 0, 1),
                    jnp.swapaxes(tlp, 0, 1), hist_f, k_pages, v_pages,
                    k_scales, v_scales)
        return toks.T, hist_f, k_pages, v_pages, k_scales, v_scales
    k_pages, v_pages = write_kv_pages_all_layers(
        k_pages, v_pages, k_acc, v_acc, page_table, commit_pos
    )
    if want_lp:
        _, lp, tids, tlp = emitted
        return (toks.T, lp.T, jnp.swapaxes(tids, 0, 1),
                jnp.swapaxes(tlp, 0, 1), hist_f, k_pages, v_pages)
    return toks.T, hist_f, k_pages, v_pages  # [B, k]


def _ngram_draft(buf, pos, n, k):
    """Prompt-lookup draft, vectorized: find the most recent earlier occurrence
    of the trailing n-gram ``buf[pos-n+1..pos]`` and return the k tokens that
    followed it. Falls back to repeating the current token (which verify will
    almost surely reject — costing nothing extra, since the verify forward has
    static width anyway).

    buf: [B, H] int32 token history; pos: [B] position of the current token.
    Returns [B, k] int32 draft tokens.
    """
    B, H = buf.shape
    S = H - n + 1
    tail_idx = jnp.clip(pos[:, None] + jnp.arange(-n + 1, 1), 0, H - 1)
    tail = jnp.take_along_axis(buf, tail_idx, axis=1)                    # [B, n]
    win_idx = jnp.arange(S)[:, None] + jnp.arange(n)[None, :]            # [S, n]
    wins = buf[:, win_idx]                                               # [B, S, n]
    match = jnp.all(wins == tail[:, None, :], axis=-1)                   # [B, S]
    starts = jnp.arange(S, dtype=jnp.int32)[None, :]
    # the match and its k following tokens must lie fully in known history
    # (this also excludes the trailing n-gram matching itself)
    ok = match & (starts + n + k - 1 <= pos[:, None])
    best = jnp.max(jnp.where(ok, starts, -1), axis=1)                    # [B]
    d_idx = jnp.clip(best[:, None] + n + jnp.arange(k), 0, H - 1)
    draft = jnp.take_along_axis(buf, d_idx, axis=1)                      # [B, k]
    cur = jnp.take_along_axis(buf, jnp.clip(pos, 0, H - 1)[:, None], axis=1)
    return jnp.where((best >= 0)[:, None], draft, cur)


def _spec_fn(forward, cfg, steps, k, n, params, k_pages, v_pages, history,
             input_ids, positions, page_table, kv_lens, kv_limits, temperature,
             top_k, top_p, key, lora=None, lora_ids=None):
    """``steps`` fused speculative rounds; see ModelRunner.step_spec.

    Like _multi_step_fn, the scan carries the batch's gathered KV block (plus
    the token-history buffer), not the whole pool. Rejected draft tokens leave
    stale KV beyond the accepted length; it is invisible (attention masks by
    kv_lens) and overwritten by the next round's writes.
    """
    B, P = page_table.shape
    pool_pages = k_pages.shape[1]
    page_size = k_pages.shape[2]
    H = history.shape[1]
    T = 1 + k
    flat = page_table.reshape(-1)
    k_blk = jnp.take(k_pages, flat, axis=1)
    v_blk = jnp.take(v_pages, flat, axis=1)
    local_pt = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P)
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    keys = jax.random.split(key, steps)
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    j = jnp.arange(T, dtype=jnp.int32)[None, :]
    rep = lambda x: jnp.repeat(x, T, axis=0)  # [B] -> [B*T] row params

    def body(carry, key_i):
        buf, pos, lens, kp, vp = carry   # pos [B]: current token's position, -1 = done
        active = (pos >= 0) & (lens + k <= kv_limits)
        p0 = jnp.maximum(pos, 0)
        cur = jnp.take_along_axis(buf, p0[:, None], axis=1)              # [B, 1]
        draft = _ngram_draft(buf, p0, n, k)                              # [B, k]
        seq_in = jnp.concatenate([cur, draft], axis=1)                   # [B, T]
        pos_in = jnp.where(active[:, None], p0[:, None] + j, -1)
        lens_in = jnp.where(active, lens + k, 0)
        logits, kp, vp = forward(
            params, cfg, seq_in, pos_in, kp, vp, local_pt, lens_in,
            all_logits=True, **kw
        )                                                                # [B, T, V]
        t = sample(
            logits.reshape(B * T, -1), key_i,
            rep(temperature), rep(top_k), rep(top_p),
        ).reshape(B, T)
        # exact rejection sampling for a deterministic draft: accept the
        # leading run of draft tokens the target also sampled; the first
        # mismatch IS the corrected token (and position k's sample is the
        # bonus token when everything was accepted)
        match = (t[:, :k] == draft).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)                  # [B] 0..k
        bonus = jnp.take_along_axis(t, m[:, None], axis=1)[:, 0]         # [B]
        upd = jnp.where(j == m[:, None], bonus[:, None],
                        jnp.concatenate([draft, cur], axis=1))           # [B, T]
        emit = active[:, None] & (j <= m[:, None])
        slots = jnp.where(emit, p0[:, None] + 1 + j, H)
        buf = buf.at[rows, slots].set(upd, mode="drop")
        toks = jnp.where(emit, upd, -1)                                  # [B, T]
        emitted = (m + 1) * active.astype(jnp.int32)
        pos = jnp.where(active, pos + emitted, -1)
        lens = lens + emitted
        return (buf, pos, lens, kp, vp), toks

    (_, _, lens_f, k_blk, v_blk), toks = jax.lax.scan(
        body, (history, positions[:, 0], kv_lens, k_blk, v_blk), keys
    )
    # scatter back the pages holding accepted tokens (stale tail beyond the
    # accepted length never needs to persist); same uniqueness argument as
    # _multi_step_fn: the written logical range covers only freshly-owned pages
    p_idx = jnp.arange(P, dtype=jnp.int32)[None, :]
    first = (kv_lens - 1) // page_size
    last = (lens_f - 1) // page_size  # padded rows: lens_f=0 -> last=-1 -> no write
    written = (p_idx >= first[:, None]) & (p_idx <= last[:, None])
    safe = jnp.where(written, page_table, pool_pages).reshape(-1)
    k_pages = k_pages.at[:, safe].set(k_blk, mode="drop")
    v_pages = v_pages.at[:, safe].set(v_blk, mode="drop")
    return jnp.transpose(toks, (1, 0, 2)), k_pages, v_pages  # [B, steps, T]


def _step_fn(forward, cfg, want_lp, want_pen, params, k_pages, v_pages,
             input_ids, positions, page_table, kv_lens, temperature, top_k,
             top_p, key, lora=None, lora_ids=None, pen=None, bias=None,
             kv_scales=None):
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    quant = kv_scales is not None
    if quant:
        kw["kv_scales"] = kv_scales
        logits, k_pages, v_pages, k_sc, v_sc = forward(
            params, cfg, input_ids, positions, k_pages, v_pages, page_table,
            kv_lens, **kw,
        )
    else:
        logits, k_pages, v_pages = forward(
            params, cfg, input_ids, positions, k_pages, v_pages, page_table,
            kv_lens, **kw,
        )
    sample_from = logits
    if want_pen:
        hist, plens, pres, freq, rep = pen
        sample_from = apply_penalties(
            logits.astype(jnp.float32), hist, kv_lens, plens, pres, freq, rep
        )
    if bias is not None:
        sample_from = apply_logit_bias(
            sample_from.astype(jnp.float32), *bias
        )
    if want_lp:
        # logprobs report the RAW distribution; penalties shape the draw only
        ids, lp, tids, tlp = sample_with_logprobs(
            logits, key, temperature, top_k, top_p, sample_from=sample_from
        )
        if quant:
            return ids, logits, lp, tids, tlp, k_pages, v_pages, k_sc, v_sc
        return ids, logits, lp, tids, tlp, k_pages, v_pages
    ids = sample(sample_from, key, temperature, top_k, top_p)
    if quant:
        return ids, logits, k_pages, v_pages, k_sc, v_sc
    return ids, logits, k_pages, v_pages
