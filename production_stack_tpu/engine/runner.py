"""ModelRunner — owns device state (params, KV page pools) and the jitted step.

One compiled program per (batch_bucket, chunk_bucket, pages_bucket) triple; the
scheduler quantizes work to those buckets so XLA never sees a new shape in
steady state. KV pools are donated every call, so XLA updates pages in place
(no pool-sized copies per token).

This is the layer the reference delegates to vLLM's model executor; the serving
contract above it (engine/api_server.py) matches the stack's expectations
(SURVEY.md §1 L4).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu import models
from production_stack_tpu.ops.sampling import sample
from production_stack_tpu.parallel import shardings
from production_stack_tpu.parallel.mesh import make_mesh


@dataclasses.dataclass
class StepInput:
    """Host-side batch description, already bucketed by the scheduler."""

    input_ids: Any      # [B, T] int32
    positions: Any      # [B, T] int32, -1 pad
    page_table: Any     # [B, max_pages] int32
    kv_lens: Any        # [B] int32 (including this step's tokens)
    temperature: Any    # [B] float32
    top_k: Any          # [B] int32
    top_p: Any          # [B] float32
    lora_ids: Any = None  # [B] int32 adapter slot (0 = base); None when LoRA off
    kv_limits: Any = None  # [B] int32 max kv_len (multi-step decode bound)


class ModelRunner:
    """Holds params + KV pools on device and runs jitted prefill/decode steps."""

    def __init__(
        self,
        cfg,
        *,
        mesh: Optional[Mesh] = None,
        params: Optional[dict] = None,
        num_pages: int = 512,
        page_size: int = 16,
        seed: int = 0,
        module=None,
        enable_lora: bool = False,
        max_loras: int = 4,
        max_lora_rank: int = 16,
        lora_targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
    ):
        self.module = module if module is not None else models.module_for_config(cfg)
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.mesh = mesh if mesh is not None else make_mesh()
        if cfg.attn_impl == "auto":
            # pallas decode kernel: single-shard meshes on real TPU only (the
            # XLA gather path partitions under GSPMD; the kernel does not yet)
            use_pallas = (
                jax.default_backend() == "tpu" and self.mesh.devices.size == 1
            )
            cfg = dataclasses.replace(
                cfg, attn_impl="pallas" if use_pallas else "xla"
            )
            self.cfg = cfg

        if params is None:
            params = self.module.init_params(cfg, jax.random.key(seed))
        pspecs = shardings.param_specs_for(params)
        self.params = shardings.shard_tree(params, pspecs, self.mesh)
        kp, vp = self.module.init_kv_pages(cfg, num_pages, page_size)
        kv_sh = NamedSharding(self.mesh, shardings.KV_PAGES_SPEC)
        self.k_pages = jax.device_put(kp, kv_sh)
        self.v_pages = jax.device_put(vp, kv_sh)
        self._rng = jax.random.key(seed)

        self.enable_lora = enable_lora
        self.max_loras = max_loras
        self.max_lora_rank = max_lora_rank
        self.lora_targets = tuple(lora_targets)
        self.lora = None
        if enable_lora:
            if not hasattr(self.module, "init_lora_buffers"):
                raise ValueError(
                    f"LoRA is not supported for model family "
                    f"{self.module.__name__.rsplit('.', 1)[-1]!r} (llama-family only)"
                )
            # slot-stacked adapter buffers, replicated (small; the deltas they
            # produce inherit the activations' sharding under GSPMD).
            # max_loras counts adapters; slot 0 is the base model, hence +1.
            buf = self.module.init_lora_buffers(
                cfg, max_loras + 1, max_lora_rank, self.lora_targets
            )
            rep = NamedSharding(self.mesh, P())
            self.lora = jax.tree.map(lambda x: jax.device_put(x, rep), buf)
            self._set_lora_fn = None  # built lazily in set_lora_slot

        self._row_sh = NamedSharding(self.mesh, shardings.BATCH_SPECS["input_ids"])
        self._vec_sh = NamedSharding(self.mesh, shardings.BATCH_SPECS["kv_lens"])
        self._step = jax.jit(
            functools.partial(_step_fn, self.module.forward, cfg),
            donate_argnums=(1, 2),
        )
        self._set_page_fn = None  # built lazily in set_page
        self._encode = None       # built lazily in encode (pooled embeddings)
        self._multi_steps: dict[int, Any] = {}  # k -> jitted k-step decode

    def _stage(self, inp: StepInput, with_limits: bool = False) -> dict:
        """Host→device staging shared by step/step_multi: split the RNG and
        device_put every input with the runner's shardings."""
        self._rng, key = jax.random.split(self._rng)
        if self.mesh.devices.size == 1:
            # single chip: hand numpy straight to the jitted call — one
            # transfer batch instead of a device_put round trip per array
            # (matters on network-attached chips)
            row = vec = lambda x, dt: np.asarray(x, np.dtype(dt))
        else:
            row = lambda x, dt: jax.device_put(jnp.asarray(x, dt), self._row_sh)
            vec = lambda x, dt: jax.device_put(jnp.asarray(x, dt), self._vec_sh)
        lora_ids = None
        if self.lora is not None:
            ids_arr = (
                inp.lora_ids
                if inp.lora_ids is not None
                else np.zeros(np.asarray(inp.kv_lens).shape, np.int32)
            )
            lora_ids = vec(ids_arr, jnp.int32)
        staged = dict(
            input_ids=row(inp.input_ids, jnp.int32),
            positions=row(inp.positions, jnp.int32),
            page_table=row(inp.page_table, jnp.int32),
            kv_lens=vec(inp.kv_lens, jnp.int32),
            temperature=vec(inp.temperature, jnp.float32),
            top_k=vec(inp.top_k, jnp.int32),
            top_p=vec(inp.top_p, jnp.float32),
            key=key,
            lora_ids=lora_ids,
        )
        if with_limits:
            B = np.asarray(inp.kv_lens).shape[0]
            limits = (
                inp.kv_limits
                if inp.kv_limits is not None
                else np.full((B,), np.iinfo(np.int32).max // 2, np.int32)
            )
            staged["kv_limits"] = vec(limits, jnp.int32)
        return staged

    def step(self, inp: StepInput) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Run one forward+sample step. Returns (token_ids [B], logits [B, V])."""
        s = self._stage(inp)
        ids, logits, self.k_pages, self.v_pages = self._step(
            self.params,
            self.k_pages,
            self.v_pages,
            s["input_ids"],
            s["positions"],
            s["page_table"],
            s["kv_lens"],
            s["temperature"],
            s["top_k"],
            s["top_p"],
            s["key"],
            self.lora,
            s["lora_ids"],
        )
        return ids, logits

    def step_multi(self, inp: StepInput, k: int) -> jnp.ndarray:
        """Run k fused decode steps in ONE device program (lax.scan feeding
        each sampled token back as the next input). Returns tokens [B, k].

        Why: on serving hosts every dispatch pays host<->device latency (and
        per-call device_puts); at decode, compute per step is a few ms, so the
        round trip dominates. Fusing k steps amortizes it k-fold — the
        TPU-native answer to the reference's multi-step scheduling knob.
        Sequences that run out of budget mid-burst (EOS handling is host-side)
        are masked via ``kv_limits``: their positions go to -1, so KV writes
        drop and attention masks, and the host discards their surplus tokens.
        """
        if k == 1:
            ids, _ = self.step(inp)
            return jnp.asarray(ids)[:, None]
        if k not in self._multi_steps:
            self._multi_steps[k] = jax.jit(
                functools.partial(_multi_step_fn, self.module.forward, self.cfg, k),
                donate_argnums=(1, 2),
            )
        s = self._stage(inp, with_limits=True)
        toks, self.k_pages, self.v_pages = self._multi_steps[k](
            self.params,
            self.k_pages,
            self.v_pages,
            s["input_ids"],
            s["positions"],
            s["page_table"],
            s["kv_lens"],
            s["kv_limits"],
            s["temperature"],
            s["top_k"],
            s["top_p"],
            s["key"],
            self.lora,
            s["lora_ids"],
        )
        return toks

    def encode(self, input_ids, positions) -> jnp.ndarray:
        """Pooled-embedding forward ([B, T] -> [B, H] unit vectors). Shapes
        must arrive bucketed (engine quantizes B and T)."""
        if self._encode is None:
            if not hasattr(self.module, "encode"):
                raise ValueError(
                    f"embeddings are not supported for model family "
                    f"{self.module.__name__.rsplit('.', 1)[-1]!r}"
                )
            self._encode = jax.jit(
                functools.partial(self.module.encode, cfg=self.cfg)
            )
        row = lambda x: jax.device_put(jnp.asarray(x, jnp.int32), self._row_sh)
        return self._encode(
            params=self.params, input_ids=row(input_ids), positions=row(positions)
        )

    # -- LoRA slot management (engine/lora.py drives these) ------------------

    def set_lora_slot(self, slot: int, tensors: dict, scale: float) -> None:
        """Write one adapter's stacked weights into `slot` in place."""
        if self.lora is None:
            raise RuntimeError("runner built with enable_lora=False")
        if not 0 < slot <= self.max_loras:
            raise ValueError(f"slot must be in [1, {self.max_loras}], got {slot}")
        if self._set_lora_fn is None:
            def _set(layers, scale_vec, slot, new_layers, new_scale):
                layers = {
                    k: (v.at[:, slot].set(new_layers[k].astype(v.dtype))
                        if k in new_layers else v)
                    for k, v in layers.items()
                }
                return layers, scale_vec.at[slot].set(new_scale)

            self._set_lora_fn = jax.jit(_set, donate_argnums=(0, 1))
        self.lora["layers"], self.lora["scale"] = self._set_lora_fn(
            self.lora["layers"], self.lora["scale"], jnp.int32(slot),
            {k: jnp.asarray(v) for k, v in tensors.items()},
            jnp.float32(scale),
        )

    def clear_lora_slot(self, slot: int) -> None:
        if self.lora is None:
            raise RuntimeError("runner built with enable_lora=False")
        # per-slot leaf shape: [L, S, d1, d2] -> [L, d1, d2]
        zeros = {
            k: np.zeros((v.shape[0],) + v.shape[2:], np.float32)
            for k, v in self.lora["layers"].items()
        }
        self.set_lora_slot(slot, zeros, 0.0)

    def get_page(self, pid: int):
        """Fetch one page's K/V to host ([L, page_size, KH, D] each)."""
        return jax.device_get((self.k_pages[:, pid], self.v_pages[:, pid]))

    def set_page(self, pid: int, k, v) -> None:
        """Write one page's K/V into the pools in place (offload restore /
        disaggregated-prefill KV injection)."""
        if self._set_page_fn is None:
            self._set_page_fn = jax.jit(
                lambda kp, vp, i, k, v: (kp.at[:, i].set(k), vp.at[:, i].set(v)),
                donate_argnums=(0, 1),
            )
        dt = self.k_pages.dtype
        self.k_pages, self.v_pages = self._set_page_fn(
            self.k_pages, self.v_pages, jnp.int32(pid),
            jnp.asarray(k, dt), jnp.asarray(v, dt),
        )

    def reset_kv(self) -> None:
        """Zero the page pools (sleep/wake support frees and re-creates them)."""
        kp, vp = self.module.init_kv_pages(self.cfg, self.num_pages, self.page_size)
        kv_sh = NamedSharding(self.mesh, shardings.KV_PAGES_SPEC)
        self.k_pages = jax.device_put(kp, kv_sh)
        self.v_pages = jax.device_put(vp, kv_sh)


def _multi_step_fn(forward, cfg, k, params, k_pages, v_pages, input_ids,
                   positions, page_table, kv_lens, kv_limits, temperature,
                   top_k, top_p, key, lora=None, lora_ids=None):
    """k fused decode steps; see ModelRunner.step_multi. input_ids/positions
    are [B, 1] (decode shape).

    The scan carries only the batch's gathered KV block, NOT the whole pool:
    XLA double-buffers while-loop carries, so carrying a multi-GB pool through
    the scan 2-3x's KV memory and OOMs real chips. The block is a local pool
    of B*P pages indexed by an identity page table, so ``forward`` is reused
    unchanged; pages the burst wrote are scattered back afterwards."""
    B, P = page_table.shape
    pool_pages = k_pages.shape[1]
    page_size = k_pages.shape[2]
    flat = page_table.reshape(-1)
    k_blk = jnp.take(k_pages, flat, axis=1)  # [L, B*P, page, KH, D]
    v_blk = jnp.take(v_pages, flat, axis=1)
    local_pt = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P)
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    keys = jax.random.split(key, k)

    def body(carry, key_i):
        ids, pos, lens, kp, vp = carry
        logits, kp, vp = forward(
            params, cfg, ids, pos, kp, vp, local_pt, lens, **kw
        )
        nxt = sample(logits, key_i, temperature, top_k, top_p)  # [B]
        # a row continues while it was active this step and has budget left
        active = (pos[:, 0] >= 0) & (lens < kv_limits)
        pos = jnp.where(active, pos[:, 0] + 1, -1)[:, None]
        lens = lens + active.astype(lens.dtype)
        ids = jnp.where(active, nxt, 0)[:, None]
        return (ids, pos, lens, kp, vp), nxt

    (_, _, lens_f, k_blk, v_blk), toks = jax.lax.scan(
        body, (input_ids, positions, kv_lens, k_blk, v_blk), keys
    )
    # scatter back only the logical pages the burst wrote
    # ([(lens0-1)//page, (lens_f-1)//page] per row): those are uniquely owned
    # by each row, so no duplicate indices; everything else in the block is an
    # unmodified copy (incl. shared prefix pages and padding), dropped via an
    # out-of-range index.
    p_idx = jnp.arange(P, dtype=jnp.int32)[None, :]
    first = (kv_lens - 1) // page_size
    last = (lens_f - 1) // page_size
    written = (p_idx >= first[:, None]) & (p_idx <= last[:, None])
    safe = jnp.where(written, page_table, pool_pages).reshape(-1)
    k_pages = k_pages.at[:, safe].set(k_blk, mode="drop")
    v_pages = v_pages.at[:, safe].set(v_blk, mode="drop")
    return toks.T, k_pages, v_pages  # [B, k]


def _step_fn(forward, cfg, params, k_pages, v_pages, input_ids, positions,
             page_table, kv_lens, temperature, top_k, top_p, key,
             lora=None, lora_ids=None):
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    logits, k_pages, v_pages = forward(
        params, cfg, input_ids, positions, k_pages, v_pages, page_table, kv_lens,
        **kw,
    )
    ids = sample(logits, key, temperature, top_k, top_p)
    return ids, logits, k_pages, v_pages
