"""ModelRunner — owns device state (params, KV page pools) and the jitted step.

One compiled program per (batch_bucket, chunk_bucket, pages_bucket) triple; the
scheduler quantizes work to those buckets so XLA never sees a new shape in
steady state. KV pools are donated every call, so XLA updates pages in place
(no pool-sized copies per token).

This is the layer the reference delegates to vLLM's model executor; the serving
contract above it (engine/api_server.py) matches the stack's expectations
(SURVEY.md §1 L4).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu import models
from production_stack_tpu.ops.sampling import sample
from production_stack_tpu.parallel import shardings
from production_stack_tpu.parallel.mesh import make_mesh


@dataclasses.dataclass
class StepInput:
    """Host-side batch description, already bucketed by the scheduler."""

    input_ids: Any      # [B, T] int32
    positions: Any      # [B, T] int32, -1 pad
    page_table: Any     # [B, max_pages] int32
    kv_lens: Any        # [B] int32 (including this step's tokens)
    temperature: Any    # [B] float32
    top_k: Any          # [B] int32
    top_p: Any          # [B] float32
    lora_ids: Any = None  # [B] int32 adapter slot (0 = base); None when LoRA off


class ModelRunner:
    """Holds params + KV pools on device and runs jitted prefill/decode steps."""

    def __init__(
        self,
        cfg,
        *,
        mesh: Optional[Mesh] = None,
        params: Optional[dict] = None,
        num_pages: int = 512,
        page_size: int = 16,
        seed: int = 0,
        module=None,
        enable_lora: bool = False,
        max_loras: int = 4,
        max_lora_rank: int = 16,
        lora_targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
    ):
        self.module = module if module is not None else models.module_for_config(cfg)
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.mesh = mesh if mesh is not None else make_mesh()
        if cfg.attn_impl == "auto":
            # pallas decode kernel: single-shard meshes on real TPU only (the
            # XLA gather path partitions under GSPMD; the kernel does not yet)
            use_pallas = (
                jax.default_backend() == "tpu" and self.mesh.devices.size == 1
            )
            cfg = dataclasses.replace(
                cfg, attn_impl="pallas" if use_pallas else "xla"
            )
            self.cfg = cfg

        if params is None:
            params = self.module.init_params(cfg, jax.random.key(seed))
        pspecs = shardings.param_specs_for(params)
        self.params = shardings.shard_tree(params, pspecs, self.mesh)
        kp, vp = self.module.init_kv_pages(cfg, num_pages, page_size)
        kv_sh = NamedSharding(self.mesh, shardings.KV_PAGES_SPEC)
        self.k_pages = jax.device_put(kp, kv_sh)
        self.v_pages = jax.device_put(vp, kv_sh)
        self._rng = jax.random.key(seed)

        self.enable_lora = enable_lora
        self.max_loras = max_loras
        self.max_lora_rank = max_lora_rank
        self.lora_targets = tuple(lora_targets)
        self.lora = None
        if enable_lora:
            if not hasattr(self.module, "init_lora_buffers"):
                raise ValueError(
                    f"LoRA is not supported for model family "
                    f"{self.module.__name__.rsplit('.', 1)[-1]!r} (llama-family only)"
                )
            # slot-stacked adapter buffers, replicated (small; the deltas they
            # produce inherit the activations' sharding under GSPMD).
            # max_loras counts adapters; slot 0 is the base model, hence +1.
            buf = self.module.init_lora_buffers(
                cfg, max_loras + 1, max_lora_rank, self.lora_targets
            )
            rep = NamedSharding(self.mesh, P())
            self.lora = jax.tree.map(lambda x: jax.device_put(x, rep), buf)
            self._set_lora_fn = None  # built lazily in set_lora_slot

        self._row_sh = NamedSharding(self.mesh, shardings.BATCH_SPECS["input_ids"])
        self._vec_sh = NamedSharding(self.mesh, shardings.BATCH_SPECS["kv_lens"])
        self._step = jax.jit(
            functools.partial(_step_fn, self.module.forward, cfg),
            donate_argnums=(1, 2),
        )
        self._set_page_fn = None  # built lazily in set_page
        self._encode = None       # built lazily in encode (pooled embeddings)

    def step(self, inp: StepInput) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Run one forward+sample step. Returns (token_ids [B], logits [B, V])."""
        self._rng, key = jax.random.split(self._rng)
        row = lambda x, dt: jax.device_put(jnp.asarray(x, dt), self._row_sh)
        vec = lambda x, dt: jax.device_put(jnp.asarray(x, dt), self._vec_sh)
        lora_ids = None
        if self.lora is not None:
            ids_arr = (
                inp.lora_ids
                if inp.lora_ids is not None
                else jnp.zeros(jnp.asarray(inp.kv_lens).shape, jnp.int32)
            )
            lora_ids = vec(ids_arr, jnp.int32)
        ids, logits, self.k_pages, self.v_pages = self._step(
            self.params,
            self.k_pages,
            self.v_pages,
            row(inp.input_ids, jnp.int32),
            row(inp.positions, jnp.int32),
            row(inp.page_table, jnp.int32),
            vec(inp.kv_lens, jnp.int32),
            vec(inp.temperature, jnp.float32),
            vec(inp.top_k, jnp.int32),
            vec(inp.top_p, jnp.float32),
            key,
            self.lora,
            lora_ids,
        )
        return ids, logits

    def encode(self, input_ids, positions) -> jnp.ndarray:
        """Pooled-embedding forward ([B, T] -> [B, H] unit vectors). Shapes
        must arrive bucketed (engine quantizes B and T)."""
        if self._encode is None:
            if not hasattr(self.module, "encode"):
                raise ValueError(
                    f"embeddings are not supported for model family "
                    f"{self.module.__name__.rsplit('.', 1)[-1]!r}"
                )
            self._encode = jax.jit(
                functools.partial(self.module.encode, cfg=self.cfg)
            )
        row = lambda x: jax.device_put(jnp.asarray(x, jnp.int32), self._row_sh)
        return self._encode(
            params=self.params, input_ids=row(input_ids), positions=row(positions)
        )

    # -- LoRA slot management (engine/lora.py drives these) ------------------

    def set_lora_slot(self, slot: int, tensors: dict, scale: float) -> None:
        """Write one adapter's stacked weights into `slot` in place."""
        if self.lora is None:
            raise RuntimeError("runner built with enable_lora=False")
        if not 0 < slot <= self.max_loras:
            raise ValueError(f"slot must be in [1, {self.max_loras}], got {slot}")
        if self._set_lora_fn is None:
            def _set(layers, scale_vec, slot, new_layers, new_scale):
                layers = {
                    k: (v.at[:, slot].set(new_layers[k].astype(v.dtype))
                        if k in new_layers else v)
                    for k, v in layers.items()
                }
                return layers, scale_vec.at[slot].set(new_scale)

            self._set_lora_fn = jax.jit(_set, donate_argnums=(0, 1))
        self.lora["layers"], self.lora["scale"] = self._set_lora_fn(
            self.lora["layers"], self.lora["scale"], jnp.int32(slot),
            {k: jnp.asarray(v) for k, v in tensors.items()},
            jnp.float32(scale),
        )

    def clear_lora_slot(self, slot: int) -> None:
        if self.lora is None:
            raise RuntimeError("runner built with enable_lora=False")
        # per-slot leaf shape: [L, S, d1, d2] -> [L, d1, d2]
        zeros = {
            k: np.zeros((v.shape[0],) + v.shape[2:], np.float32)
            for k, v in self.lora["layers"].items()
        }
        self.set_lora_slot(slot, zeros, 0.0)

    def get_page(self, pid: int):
        """Fetch one page's K/V to host ([L, page_size, KH, D] each)."""
        return jax.device_get((self.k_pages[:, pid], self.v_pages[:, pid]))

    def set_page(self, pid: int, k, v) -> None:
        """Write one page's K/V into the pools in place (offload restore /
        disaggregated-prefill KV injection)."""
        if self._set_page_fn is None:
            self._set_page_fn = jax.jit(
                lambda kp, vp, i, k, v: (kp.at[:, i].set(k), vp.at[:, i].set(v)),
                donate_argnums=(0, 1),
            )
        dt = self.k_pages.dtype
        self.k_pages, self.v_pages = self._set_page_fn(
            self.k_pages, self.v_pages, jnp.int32(pid),
            jnp.asarray(k, dt), jnp.asarray(v, dt),
        )

    def reset_kv(self) -> None:
        """Zero the page pools (sleep/wake support frees and re-creates them)."""
        kp, vp = self.module.init_kv_pages(self.cfg, self.num_pages, self.page_size)
        kv_sh = NamedSharding(self.mesh, shardings.KV_PAGES_SPEC)
        self.k_pages = jax.device_put(kp, kv_sh)
        self.v_pages = jax.device_put(vp, kv_sh)


def _step_fn(forward, cfg, params, k_pages, v_pages, input_ids, positions,
             page_table, kv_lens, temperature, top_k, top_p, key,
             lora=None, lora_ids=None):
    kw = {} if lora is None else {"lora": lora, "lora_ids": lora_ids}
    logits, k_pages, v_pages = forward(
        params, cfg, input_ids, positions, k_pages, v_pages, page_table, kv_lens,
        **kw,
    )
    ids = sample(logits, key, temperature, top_k, top_p)
    return ids, logits, k_pages, v_pages
