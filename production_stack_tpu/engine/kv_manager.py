"""Host-side KV page accounting: allocator + chunk-hash prefix cache.

The device holds the page *pools* (engine/runner.py); this module decides which
physical pages each sequence owns. Prefix caching is page-granular and keyed by
a rolling blake2b chain over full pages of token ids — the same chunk-hash
scheme the router's prefix trie and the KV-index controller use, so routing,
engine cache, and offload tiers agree on identity (SURVEY.md §7 hard part #3:
"chunk hashing consistent between router trie, engine prefix cache, and
KV-index controller").

Reference parity: vLLM's `--enable-prefix-caching` + LMCache chunk reuse, as
enabled by helm/templates/deployment-vllm-multi.yaml:137-141 in /root/reference.
"""

from __future__ import annotations

import hashlib
import heapq
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from production_stack_tpu.tracing import get_flightrecorder


def chunk_hash(prev_hash: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(prev_hash, digest_size=16)
    h.update(b"".join(int(t).to_bytes(4, "little", signed=True) for t in tokens))
    return h.digest()


def prefix_hashes(
    tokens: Sequence[int], page_size: int, salt: bytes = b""
) -> list[bytes]:
    """Hash chain over full pages of `tokens` (len // page_size entries).

    ``salt`` seeds the chain; LoRA requests salt with the adapter name because
    adapters change wk/wv and hence the KV contents — pages must never be
    shared across adapters (or with the base model)."""
    out, h = [], salt
    for i in range(len(tokens) // page_size):
        h = chunk_hash(h, tokens[i * page_size : (i + 1) * page_size])
        out.append(h)
    return out


@dataclass
class PageInfo:
    ref_count: int = 0
    hash: Optional[bytes] = None  # set once the page is full + hashable
    hits: int = 0                 # times served from the prefix cache
    depth: int = 0                # page index in its prefix chain (0 = head)
    last_used: float = 0.0        # monotonic, refreshed on every cache hit
    offloaded: bool = False       # blob already saved to the offload tier


class KVPageManager:
    """Reference-counted page allocator with a hot-prefix-protecting cache.

    - ``allocate(n)`` / ``free(pages)``: plain paged allocation.
    - ``match_prefix(tokens)``: longest cached page-aligned prefix -> shared
      (ref-counted) pages. Cached pages with ref_count 0 live in an evictable
      pool and are reclaimed only when a fresh allocation needs them.

    Eviction is NOT pure LRU. Free order puts a finished sequence's chain
    HEAD pages into the pool before its tail, so LRU evicted the most
    shareable pages first — measured at 107% page-pool occupancy the prefix
    hit rate collapsed to 0.24 with ~2/3 of every prompt recomputed. Instead
    every evictable page carries a reuse score (hit count decayed by recency,
    plus a shared-prefix head bonus) and eviction takes the COLDEST page
    first: one-shot tails churn while hot shared prefixes stay resident, so
    >100% occupancy degrades smoothly. ``proactive_spill`` additionally
    copies the coldest evictable pages to the offload tier once usage
    crosses ``spill_watermark`` — the eventual eviction then frees the slot
    without a blocking device fetch, heading off the allocation-stall
    preemption storms of a spill done at the last possible moment.
    """

    # hotness half-life: a page's accumulated hits decay with time since its
    # last use, so a prefix that stops being requested eventually loses its
    # protection instead of pinning pool space forever
    HIT_DECAY_S = 600.0

    def __init__(
        self, num_pages: int, page_size: int, offload=None,
        max_io_pages: int = 0, spill_watermark: float = 0.9,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        # per-operation offload I/O budget (pages); 0 = unbounded. See
        # EngineConfig.kv_offload_max_io_pages: on slow host<->device links
        # recompute beats restore past a few pages, and an uncapped spill
        # batch stalls the engine loop for the whole fetch.
        self.max_io_pages = max_io_pages
        # usage fraction past which proactive spill engages (0 or >=1 disable)
        self.spill_watermark = spill_watermark
        self.pages = [PageInfo() for _ in range(num_pages)]
        self.free_list: list[int] = list(  # owned-by: device-thread
            range(num_pages - 1, -1, -1)
        )
        self.hash_to_page: dict[bytes, int] = {}
        # pages with ref_count==0 but still holding reusable KV. Victim
        # selection goes through a lazy min-heap keyed by reuse score; the
        # token map invalidates stale heap entries (a page re-referenced and
        # re-freed gets a fresh entry, the old one is skipped on pop).
        self.evictable: dict[int, None] = {}
        self._evict_heap: list[tuple[float, int, int]] = []  # (score, token, pid)
        self._heap_token: dict[int, int] = {}
        self._token_counter = 0
        self._heap_refreshed_at = time.monotonic()
        # unspilled-work flag gating proactive_spill's candidate scan
        self._spill_dirty = False
        self.prefix_queries = 0
        self.prefix_hits = 0  # counted in pages
        self.offload_hits = 0  # pages restored from the offload tiers
        self.evicted_pages_total = 0
        # pages evicted DESPITE a nonzero hit count — hot-prefix casualties;
        # a rising rate means the pool is too small for the hot set
        self.evicted_hot_pages_total = 0
        self.proactive_spilled_pages_total = 0
        # KVOffloadConnector (kvoffload/connector.py): spill evicted pages to
        # host DRAM/disk/remote and restore them on later prefix matches
        self.offload = offload
        # fleet-wide KV directory publisher (kvdirectory.DirectoryPublisher,
        # wired by LLMEngine when --kv-directory-url is set): prefix-cache
        # inserts publish resident claims, confirmed spills publish shared
        # claims, evictions withdraw — all dirty-batched off-thread
        self.directory = None

    # -- eviction policy ----------------------------------------------------

    def _evict_score(self, info: PageInfo) -> float:
        """Reuse score; eviction takes the LOWEST first. Hits (decayed by
        time since last use) dominate, so any recently-hit page outlives
        every cold one; among cold pages the head bonus (1/(1+depth)) makes
        chain TAILS go first — a chain can only restore/re-share from its
        head, so a surviving head keeps value a surviving tail does not."""
        age = max(0.0, time.monotonic() - info.last_used)
        return info.hits * 0.5 ** (age / self.HIT_DECAY_S) + 1.0 / (1.0 + info.depth)

    def _make_evictable(self, pid: int) -> None:
        info = self.pages[pid]
        self._token_counter += 1
        self._heap_token[pid] = self._token_counter
        heapq.heappush(
            self._evict_heap, (self._evict_score(info), self._token_counter, pid)
        )
        self.evictable[pid] = None
        # stale entries (page re-referenced then re-freed) are normally
        # purged on pop — but a pool running BELOW capacity never pops, and
        # a hot prefix cycling through the pool would leak one tuple per
        # hit forever. Compact when stale entries dominate (amortized O(1);
        # AFTER registering pid so the rebuild includes it).
        if len(self._evict_heap) > 2 * len(self.evictable) + 64:
            self._refresh_heap(time.monotonic())
        if info.hash is not None and not info.offloaded:
            self._spill_dirty = True

    def _remove_evictable(self, pid: int) -> None:
        del self.evictable[pid]
        self._heap_token.pop(pid, None)  # stale heap entries skip on pop

    def _refresh_heap(self, now: float) -> None:
        """Rebuild the heap with CURRENT scores. Entries carry the score
        computed when the page entered the pool; recency decay since then is
        invisible to the heap ordering, so an abandoned hot prefix would
        otherwise keep its stale high score (and its protection) forever.
        One O(E) rebuild per HIT_DECAY_S bounds the staleness to a single
        half-life — exactly the granularity the decay is meant to act at."""
        self._evict_heap = []
        self._heap_token.clear()
        for pid in self.evictable:
            self._token_counter += 1
            self._heap_token[pid] = self._token_counter
            self._evict_heap.append(
                (self._evict_score(self.pages[pid]), self._token_counter, pid)
            )
        heapq.heapify(self._evict_heap)
        self._heap_refreshed_at = now

    def _pop_coldest(self) -> int:
        """Pop the lowest-score evictable page (lazy heap: entries whose page
        left the pool since push are skipped; scores older than one decay
        half-life are refreshed wholesale first)."""
        now = time.monotonic()
        if now - self._heap_refreshed_at > self.HIT_DECAY_S:
            self._refresh_heap(now)
        while self._evict_heap:
            _, token, pid = heapq.heappop(self._evict_heap)
            if self._heap_token.get(pid) == token:
                del self._heap_token[pid]
                del self.evictable[pid]
                return pid
        raise AssertionError("evictable pool and heap out of sync")

    # -- allocation ---------------------------------------------------------

    def num_free(self) -> int:
        return len(self.free_list) + len(self.evictable)

    def usage(self) -> float:
        return 1.0 - self.num_free() / self.num_pages

    def allocate(self, n: int) -> Optional[list[int]]:
        if self.num_free() < n:
            return None
        out, spill = [], []
        # flight-recorder accounting for this allocation's evictions (one
        # event per evicting allocate call, not per page — the batch IS the
        # engine-level action); scores only gathered when the recorder is on
        fr = get_flightrecorder()
        n_evicted = n_hot = 0
        evict_scores: list = []
        # directory withdrawal accounting: evicted-with-restorable-blob
        # hashes lose only their RESIDENT claim (the shared-tier claim stays
        # truthful); evicted-without-blob hashes withdraw entirely
        w_resident: list = []
        w_all: list = []
        for _ in range(n):
            if self.free_list:
                pid = self.free_list.pop()
            else:  # evict the coldest reusable page (reuse-score policy)
                pid = self._pop_coldest()
                info = self.pages[pid]
                self.evicted_pages_total += 1
                n_evicted += 1
                if fr.enabled and len(evict_scores) < 8:
                    evict_scores.append(round(self._evict_score(info), 4))
                if info.hits > 0:
                    self.evicted_hot_pages_total += 1
                    n_hot += 1
                if info.hash is not None:
                    # already-offloaded pages (proactive spill / earlier
                    # restore) skip the spill batch — their blob is in the
                    # tier, so the slot frees with zero device I/O
                    if info.offloaded:
                        w_resident.append(info.hash)
                    elif self.offload is not None:
                        spill.append((pid, info.hash, info.depth))
                    else:
                        w_all.append(info.hash)
                    self.hash_to_page.pop(info.hash, None)
                    info.hash = None
                info.hits = 0
                info.depth = 0
                info.offloaded = False
            self.pages[pid].ref_count = 1
            out.append(pid)
        if spill:
            # batched: one device fetch for the whole eviction set, not one
            # ~100 ms host<->device round trip per page (connector.save_pages).
            # Over budget, chain HEADS spill (lowest depth first) — a prefix
            # chain can only restore from its head (the tail past the cap
            # recomputes, or re-shares if still in HBM). The rest are
            # dropped + reported evicted so the global KV index stays
            # truthful.
            spill.sort(key=lambda t: t[2])
            depths = {h: d for _, h, d in spill}
            spill = [(pid, h) for pid, h, _ in spill]
            cap = self.max_io_pages
            if cap and len(spill) > cap:
                dropped = spill[cap:]
                spill = spill[:cap]
                self.offload.report_evict([h for _, h in dropped])
                w_all.extend(h for _, h in dropped)
            import time as time_mod

            from production_stack_tpu import tracing

            t_wall, t0 = time_mod.time(), time_mod.perf_counter()
            saved = self.offload.save_pages(spill)
            # directory truthfulness mirrors the offloaded-flag contract:
            # only CONFIRMED saves advertise a restorable shared claim; a
            # mid-batch tier failure withdraws the rest outright
            shared_pub: list = []
            for _, h in spill:
                if saved is None or h in saved:
                    w_resident.append(h)
                    shared_pub.append((h, depths.get(h, 0), 0.0))
                else:
                    w_all.append(h)
            if self.directory is not None and shared_pub:
                self.directory.publish_shared(shared_pub)
            # spill span under whichever request's admission forced the
            # eviction (scheduler publishes it); decode-growth evictions
            # carry no ambient context and record nothing
            ctx = tracing.current_context()
            if ctx is not None:
                tracing.get_collector().record(
                    "engine.kv_spill", ctx.child(), t_wall,
                    time_mod.perf_counter() - t0, pages=len(spill),
                )
        if n_evicted and fr.enabled:
            from production_stack_tpu import tracing as _tr

            ctx = _tr.current_context()
            fr.record(
                "kv", op="evict", pages=n_evicted, hot=n_hot,
                spilled=len(spill), victim_scores=evict_scores,
                usage=round(self.usage(), 4),
                trace_id=ctx.trace_id if ctx is not None else None,
            )
        if self.directory is not None:
            if w_resident:
                self.directory.withdraw(w_resident, "resident")
            if w_all:
                self.directory.withdraw(w_all, "all")
        return out

    def free(self, page_ids: Sequence[int]) -> None:
        for pid in page_ids:
            info = self.pages[pid]
            info.ref_count -= 1
            assert info.ref_count >= 0, f"double free of page {pid}"
            if info.ref_count == 0:
                if info.hash is not None:
                    self._make_evictable(pid)  # keep KV for reuse
                else:
                    self.free_list.append(pid)

    def proactive_spill(self) -> int:
        """Copy the coldest evictable pages' KV to the offload tier while
        they are still cache-resident, once usage crosses the high
        watermark. The pages stay matchable in HBM; their eventual eviction
        then frees the slot with no blocking device fetch (allocate skips
        ``offloaded`` pages), so an allocation storm at >100% occupancy no
        longer stalls the engine loop into a preemption storm. Bounded per
        call by ``max_io_pages`` (64 when unbounded); cheap no-op until the
        watermark is crossed AND unspilled evictable work exists. The
        watermark is measured against the TRULY-free list (``usage()`` counts
        evictable pages as free, and a pool full of cached-but-evictable KV
        is exactly the state to pre-spill): free slots below
        (1 - watermark) of the pool means the next allocation burst must
        evict."""
        if (
            self.offload is None
            or not self._spill_dirty
            or not 0.0 < self.spill_watermark < 1.0
            or len(self.free_list) > (1.0 - self.spill_watermark) * self.num_pages
        ):
            return 0
        cap = self.max_io_pages or 64
        # O(E log cap) selection, not a full sort: this runs on the scheduler
        # step path whenever the watermark holds and unspilled work exists
        unspilled = [
            pid for pid in self.evictable
            if self.pages[pid].hash is not None and not self.pages[pid].offloaded
        ]
        cands = heapq.nsmallest(
            cap, ((self._evict_score(self.pages[pid]), pid) for pid in unspilled)
        )
        batch = [(pid, self.pages[pid].hash) for _, pid in cands]
        self._spill_dirty = len(unspilled) > len(batch)
        if not batch:
            return 0
        # flip to the zero-I/O eviction path only for CONFIRMED saves — a
        # mid-batch tier failure marking unsaved pages would silently lose
        # their KV at eviction time (the blob the skip relies on never made
        # it into the tier)
        saved = self.offload.save_pages(batch)
        n = 0
        shared_pub = []
        for pid, h in batch:
            if saved is None or h in saved:  # None: legacy offload stubs
                self.pages[pid].offloaded = True
                n += 1
                info = self.pages[pid]
                shared_pub.append((h, info.depth, info.hits))
        if self.directory is not None and shared_pub:
            # proactively-spilled pages stay HBM-resident AND restorable:
            # advertise the shared claim (the resident one already exists)
            self.directory.publish_shared(shared_pub)
        if n < len(batch):
            # unconfirmed saves stay on the dirty list: the flag was computed
            # from the PLANNED batch, and leaving it False would park those
            # pages until some unrelated free() — re-arming retries them next
            # call (the tier may have recovered)
            self._spill_dirty = True
        self.proactive_spilled_pages_total += n
        if n:
            get_flightrecorder().record(
                "kv", op="spill", pages=n, planned=len(batch),
                usage=round(self.usage(), 4),
            )
        return n

    # -- prefix cache -------------------------------------------------------

    def match_prefix(
        self, tokens: Sequence[int], salt: bytes = b""
    ) -> tuple[list[int], int]:
        """Longest cached prefix of `tokens` (page-aligned).

        Returns (shared_page_ids, num_cached_tokens). Increments ref counts of
        the returned pages (caller owns them until `free`).
        """
        hashes = prefix_hashes(tokens, self.page_size, salt)
        self.prefix_queries += max(len(hashes), 1)
        now = time.monotonic()
        shared: list[int] = []
        for h in hashes:
            pid = self.hash_to_page.get(h)
            if pid is None:
                break
            info = self.pages[pid]
            if info.ref_count == 0 and pid in self.evictable:
                self._remove_evictable(pid)
            info.ref_count += 1
            info.hits += 1
            info.last_used = now
            shared.append(pid)
        if self.offload is not None:
            shared = self._extend_from_offload(hashes, shared)
        self.prefix_hits += len(shared)
        return shared, len(shared) * self.page_size

    def _extend_from_offload(
        self, hashes: list[bytes], shared: list[int]
    ) -> list[int]:
        """Extend an HBM prefix match from the offload tiers — BATCHED.

        Plans the whole chain extension first (HBM re-shares interleaved with
        tier restores), then restores every needed page through ONE
        host->device upload + scatter per <=64 pages
        (connector.load_pages). The per-page restore this replaces paid a
        full host<->device round trip (~100 ms network-attached) per page —
        an 8k-token history (128 pages) would have taken >10 s to restore.
        """
        # plan the longest contiguous extension: share pages already (back)
        # in HBM, restore tier-resident ones; stop at the first miss
        plan: list[tuple[bytes, Optional[int]]] = []  # (hash, pid | None)
        n_restores = 0
        now = time.monotonic()
        for h in hashes[len(shared):]:
            pid = self.hash_to_page.get(h)
            if pid is not None:
                # chunk re-appeared in HBM further along the chain (e.g.
                # registered by a later request) — share it, don't restore.
                # Ref it NOW so planning's own allocations can't evict it.
                info = self.pages[pid]
                if info.ref_count == 0 and pid in self.evictable:
                    self._remove_evictable(pid)
                info.ref_count += 1
                info.hits += 1
                info.last_used = now
                plan.append((h, pid))
            elif self.offload.has(h):
                if self.max_io_pages and n_restores >= self.max_io_pages:
                    # restore budget exhausted: truncate the chain here — on
                    # a slow link the remaining prefix RECOMPUTES faster
                    # than it restores (EngineConfig.kv_offload_max_io_pages).
                    # Checked only when a restore is actually NEEDED: pages
                    # still HBM-resident keep sharing for free above.
                    break
                plan.append((h, None))
                n_restores += 1
            else:
                break
        # allocate slots for every restore; shrink the plan from the tail
        # until the allocation fits (dropping a share un-refs it)
        restore_pids: list[int] = []
        while plan:
            n_restore = sum(1 for _, p in plan if p is None)
            if n_restore == 0:
                break
            got = self.allocate(n_restore)
            if got is not None:
                restore_pids = got
                break
            h, pid = plan.pop()
            if pid is not None:
                self.free([pid])
        n_restore = len(restore_pids)
        restored = 0
        if n_restore:
            import time as time_mod

            from production_stack_tpu import tracing

            t_wall, t0 = time_mod.time(), time_mod.perf_counter()
            restored = self.offload.load_pages(
                list(zip(restore_pids, (h for h, p in plan if p is None)))
            )
            dt = time_mod.perf_counter() - t0
            # restore latency is a first-class phase: histogram always
            # (dashboard phase panels), span when the admission is traced
            tracing.offload_restore_hist.observe(dt)
            ctx = tracing.current_context()
            if ctx is not None:
                tracing.get_collector().record(
                    "engine.kv_restore", ctx.child(), t_wall, dt,
                    pages_planned=n_restore, pages_restored=restored,
                )
            tracing.get_flightrecorder().record(
                "kv", op="restore", pages_planned=n_restore,
                pages_restored=restored, seconds=round(dt, 4),
                trace_id=ctx.trace_id if ctx is not None else None,
            )
        # stitch the final chain: a failed restore truncates it there;
        # shares past the truncation un-ref, unused restore slots free
        ri = 0
        broke = False
        resident_pub = []
        for h, pid in plan:
            if broke:
                if pid is not None:
                    self.free([pid])
            elif pid is not None:
                shared.append(pid)
            elif ri < restored:
                rp = restore_pids[ri]
                ri += 1
                info = self.pages[rp]
                info.hash = h
                info.depth = len(shared)  # position in the restored chain
                info.hits = 1
                info.last_used = now
                info.offloaded = True  # blob still lives in the tier
                self.hash_to_page[h] = rp
                shared.append(rp)
                self.offload_hits += 1
                resident_pub.append((h, info.depth, 1.0))
            else:
                broke = True
        if ri < n_restore:
            self.free(restore_pids[ri:])  # unhashed -> back to the free list
        if self.directory is not None and resident_pub:
            # tier-restored chunks are back in THIS engine's HBM — the
            # fleet directory should route matching prefixes here now
            self.directory.publish_resident(resident_pub)
        return shared

    # -- warm start (kvoffload/warmstart.py) --------------------------------

    def warm_candidates(
        self, max_pages: int
    ) -> "list[tuple[int, bytes, int, float]]":
        """The pages a warm-start manifest should cover: every hashed page
        (cached-evictable AND still-referenced — a full page's contents are
        immutable once hashed), ordered by reuse score DESC then chain depth
        ASC and capped at ``max_pages``. The depth tiebreak mirrors the
        capped-spill rule: a chain can only restore from its head, so under
        a cap the heads are what must survive. Returns
        ``(pid, hash, depth, hits)`` tuples — ``hits`` is the recency-DECAYED
        hit count WITHOUT the head bonus, because warm_restore feeds it back
        into ``PageInfo.hits`` and ``_evict_score`` re-adds the depth bonus;
        storing the full score would double-count it and skew post-restart
        eviction toward fresher, genuinely-hot pages."""
        now = time.monotonic()

        def decayed_hits(info: PageInfo) -> float:
            age = max(0.0, now - info.last_used)
            return info.hits * 0.5 ** (age / self.HIT_DECAY_S)

        # top-k selection, not a full sort: this runs on the engine device
        # thread every warm_start_interval_s (same reasoning as
        # proactive_spill's nsmallest) — O(H log cap) over hashed pages
        cands = heapq.nsmallest(
            max(0, max_pages),
            (
                (-self._evict_score(self.pages[pid]), self.pages[pid].depth, pid, h)
                for h, pid in self.hash_to_page.items()
            ),
        )
        return [
            (pid, h, d, decayed_hits(self.pages[pid])) for _, d, pid, h in cands
        ]

    def warm_restore(self, entries, loader) -> int:
        """Rebuild prefix-cache state from a warm-start manifest: allocate
        slots, pull the blobs through ``loader`` (connector.load_pages_sparse
        — per-entry best-effort, batched device upload), and register each
        restored page under its chunk hash with its manifest depth and reuse
        score. Restored pages enter the pool EVICTABLE (nothing references
        them yet), so a cold boot under immediate load degrades exactly like
        a warm cache would. Returns the number of pages restored."""
        todo = [
            (h, d, s) for h, d, s in entries if h not in self.hash_to_page
        ]
        # at boot the pool is empty; cap defensively anyway so a manifest
        # larger than the pool cannot force evictions of fresher state
        todo = todo[: self.num_free()]
        if not todo:
            return 0
        pids = self.allocate(len(todo))
        if pids is None:  # cannot happen given the cap; stay safe
            return 0
        ok = loader([(pid, h) for pid, (h, _, _) in zip(pids, todo)])
        now = time.monotonic()
        restored = 0
        for pid, (h, depth, score), good in zip(pids, todo, ok):
            if not good:
                continue  # free() below returns the unhashed slot to the pool
            info = self.pages[pid]
            info.hash = h
            info.depth = depth
            # the manifest's decayed hit count seeds hits so restored
            # prefixes keep their relative eviction protection (the depth
            # bonus is re-added by _evict_score, not stored)
            info.hits = score
            info.last_used = now
            info.offloaded = True  # the blob is (still) in the tier
            self.hash_to_page[h] = pid
            restored += 1
        # hashed pages land in the evictable pool; failed ones free outright
        self.free(pids)
        if self.directory is not None and restored:
            self.directory.publish_resident([
                (h, d, s) for (h, d, s), good in zip(todo, ok) if good
            ])
        if restored:
            get_flightrecorder().record(
                "kv", op="warm_restore", pages=restored, planned=len(todo)
            )
        return restored

    def register_filled(
        self, tokens: Sequence[int], page_ids: Sequence[int], salt: bytes = b""
    ) -> None:
        """Record hashes for fully-written pages of a sequence so later
        requests can share them. Called after prefill completes."""
        hashes = prefix_hashes(tokens, self.page_size, salt)
        now = time.monotonic()
        new: list[bytes] = []
        new_pub: list = []
        for depth, (h, pid) in enumerate(zip(hashes, page_ids)):
            info = self.pages[pid]
            if info.hash is None and h not in self.hash_to_page:
                info.hash = h
                info.depth = depth
                info.hits = 0
                info.last_used = now
                info.offloaded = False
                self.hash_to_page[h] = pid
                new.append(h)
                new_pub.append((h, depth, 0.0))
        if self.offload is not None and new:
            self.offload.report_admit(new)  # global KV index (kvaware routing)
        if self.directory is not None and new_pub:
            # prefix-cache insert -> fleet-directory resident claim
            self.directory.publish_resident(new_pub)

    def hit_rate(self) -> float:
        return self.prefix_hits / self.prefix_queries if self.prefix_queries else 0.0
