"""Host-side KV page accounting: allocator + chunk-hash prefix cache.

The device holds the page *pools* (engine/runner.py); this module decides which
physical pages each sequence owns. Prefix caching is page-granular and keyed by
a rolling blake2b chain over full pages of token ids — the same chunk-hash
scheme the router's prefix trie and the KV-index controller use, so routing,
engine cache, and offload tiers agree on identity (SURVEY.md §7 hard part #3:
"chunk hashing consistent between router trie, engine prefix cache, and
KV-index controller").

Reference parity: vLLM's `--enable-prefix-caching` + LMCache chunk reuse, as
enabled by helm/templates/deployment-vllm-multi.yaml:137-141 in /root/reference.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional, Sequence


def chunk_hash(prev_hash: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(prev_hash, digest_size=16)
    h.update(b"".join(int(t).to_bytes(4, "little", signed=True) for t in tokens))
    return h.digest()


def prefix_hashes(
    tokens: Sequence[int], page_size: int, salt: bytes = b""
) -> list[bytes]:
    """Hash chain over full pages of `tokens` (len // page_size entries).

    ``salt`` seeds the chain; LoRA requests salt with the adapter name because
    adapters change wk/wv and hence the KV contents — pages must never be
    shared across adapters (or with the base model)."""
    out, h = [], salt
    for i in range(len(tokens) // page_size):
        h = chunk_hash(h, tokens[i * page_size : (i + 1) * page_size])
        out.append(h)
    return out


@dataclass
class PageInfo:
    ref_count: int = 0
    hash: Optional[bytes] = None  # set once the page is full + hashable


class KVPageManager:
    """Reference-counted page allocator with an LRU prefix cache.

    - ``allocate(n)`` / ``free(pages)``: plain paged allocation.
    - ``match_prefix(tokens)``: longest cached page-aligned prefix -> shared
      (ref-counted) pages. Cached pages with ref_count 0 live in an LRU pool
      and are evicted only when a fresh allocation needs them.
    """

    def __init__(self, num_pages: int, page_size: int, offload=None):
        self.num_pages = num_pages
        self.page_size = page_size
        self.pages = [PageInfo() for _ in range(num_pages)]
        self.free_list: list[int] = list(range(num_pages - 1, -1, -1))
        self.hash_to_page: dict[bytes, int] = {}
        # pages with ref_count==0 but still holding reusable KV, LRU order
        self.evictable: OrderedDict[int, None] = OrderedDict()
        self.prefix_queries = 0
        self.prefix_hits = 0  # counted in pages
        self.offload_hits = 0  # pages restored from the offload tiers
        # KVOffloadConnector (kvoffload/connector.py): spill evicted pages to
        # host DRAM/disk/remote and restore them on later prefix matches
        self.offload = offload

    # -- allocation ---------------------------------------------------------

    def num_free(self) -> int:
        return len(self.free_list) + len(self.evictable)

    def usage(self) -> float:
        return 1.0 - self.num_free() / self.num_pages

    def allocate(self, n: int) -> Optional[list[int]]:
        if self.num_free() < n:
            return None
        out = []
        for _ in range(n):
            if self.free_list:
                pid = self.free_list.pop()
            else:  # evict oldest reusable page
                pid, _ = self.evictable.popitem(last=False)
                info = self.pages[pid]
                if info.hash is not None:
                    if self.offload is not None:  # spill KV before slot reuse
                        self.offload.save_page(pid, info.hash)
                    self.hash_to_page.pop(info.hash, None)
                    info.hash = None
            self.pages[pid].ref_count = 1
            out.append(pid)
        return out

    def free(self, page_ids: Sequence[int]) -> None:
        for pid in page_ids:
            info = self.pages[pid]
            info.ref_count -= 1
            assert info.ref_count >= 0, f"double free of page {pid}"
            if info.ref_count == 0:
                if info.hash is not None:
                    self.evictable[pid] = None  # keep KV for reuse
                else:
                    self.free_list.append(pid)

    # -- prefix cache -------------------------------------------------------

    def match_prefix(
        self, tokens: Sequence[int], salt: bytes = b""
    ) -> tuple[list[int], int]:
        """Longest cached prefix of `tokens` (page-aligned).

        Returns (shared_page_ids, num_cached_tokens). Increments ref counts of
        the returned pages (caller owns them until `free`).
        """
        hashes = prefix_hashes(tokens, self.page_size, salt)
        self.prefix_queries += max(len(hashes), 1)
        shared: list[int] = []
        for h in hashes:
            pid = self.hash_to_page.get(h)
            if pid is None:
                break
            info = self.pages[pid]
            if info.ref_count == 0:
                self.evictable.pop(pid, None)
            info.ref_count += 1
            shared.append(pid)
        if self.offload is not None:
            # extend the match from the offload tiers: restore chunk-by-chunk
            # into freshly allocated pages until the chain misses
            for h in hashes[len(shared):]:
                pid = self.hash_to_page.get(h)
                if pid is not None:
                    # chunk re-appeared in HBM further along the chain (e.g.
                    # registered by a later request) — share it, don't restore
                    info = self.pages[pid]
                    if info.ref_count == 0:
                        self.evictable.pop(pid, None)
                    info.ref_count += 1
                    shared.append(pid)
                    continue
                if not self.offload.has(h):
                    break
                got = self.allocate(1)
                if got is None:
                    break
                pid = got[0]
                if not self.offload.load_page(pid, h):
                    self.free([pid])  # blob vanished between has() and get()
                    break
                info = self.pages[pid]
                info.hash = h
                self.hash_to_page[h] = pid
                shared.append(pid)
                self.offload_hits += 1
        self.prefix_hits += len(shared)
        return shared, len(shared) * self.page_size

    def register_filled(
        self, tokens: Sequence[int], page_ids: Sequence[int], salt: bytes = b""
    ) -> None:
        """Record hashes for fully-written pages of a sequence so later
        requests can share them. Called after prefill completes."""
        hashes = prefix_hashes(tokens, self.page_size, salt)
        new: list[bytes] = []
        for h, pid in zip(hashes, page_ids):
            info = self.pages[pid]
            if info.hash is None and h not in self.hash_to_page:
                info.hash = h
                self.hash_to_page[h] = pid
                new.append(h)
        if self.offload is not None and new:
            self.offload.report_admit(new)  # global KV index (kvaware routing)

    def hit_rate(self) -> float:
        return self.prefix_hits / self.prefix_queries if self.prefix_queries else 0.0
