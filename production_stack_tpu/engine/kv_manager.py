"""Host-side KV page accounting: allocator + chunk-hash prefix cache.

The device holds the page *pools* (engine/runner.py); this module decides which
physical pages each sequence owns. Prefix caching is page-granular and keyed by
a rolling blake2b chain over full pages of token ids — the same chunk-hash
scheme the router's prefix trie and the KV-index controller use, so routing,
engine cache, and offload tiers agree on identity (SURVEY.md §7 hard part #3:
"chunk hashing consistent between router trie, engine prefix cache, and
KV-index controller").

Reference parity: vLLM's `--enable-prefix-caching` + LMCache chunk reuse, as
enabled by helm/templates/deployment-vllm-multi.yaml:137-141 in /root/reference.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional, Sequence


def chunk_hash(prev_hash: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(prev_hash, digest_size=16)
    h.update(b"".join(int(t).to_bytes(4, "little", signed=True) for t in tokens))
    return h.digest()


def prefix_hashes(
    tokens: Sequence[int], page_size: int, salt: bytes = b""
) -> list[bytes]:
    """Hash chain over full pages of `tokens` (len // page_size entries).

    ``salt`` seeds the chain; LoRA requests salt with the adapter name because
    adapters change wk/wv and hence the KV contents — pages must never be
    shared across adapters (or with the base model)."""
    out, h = [], salt
    for i in range(len(tokens) // page_size):
        h = chunk_hash(h, tokens[i * page_size : (i + 1) * page_size])
        out.append(h)
    return out


@dataclass
class PageInfo:
    ref_count: int = 0
    hash: Optional[bytes] = None  # set once the page is full + hashable


class KVPageManager:
    """Reference-counted page allocator with an LRU prefix cache.

    - ``allocate(n)`` / ``free(pages)``: plain paged allocation.
    - ``match_prefix(tokens)``: longest cached page-aligned prefix -> shared
      (ref-counted) pages. Cached pages with ref_count 0 live in an LRU pool
      and are evicted only when a fresh allocation needs them.
    """

    def __init__(
        self, num_pages: int, page_size: int, offload=None,
        max_io_pages: int = 0,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        # per-operation offload I/O budget (pages); 0 = unbounded. See
        # EngineConfig.kv_offload_max_io_pages: on slow host<->device links
        # recompute beats restore past a few pages, and an uncapped spill
        # batch stalls the engine loop for the whole fetch.
        self.max_io_pages = max_io_pages
        self.pages = [PageInfo() for _ in range(num_pages)]
        self.free_list: list[int] = list(range(num_pages - 1, -1, -1))
        self.hash_to_page: dict[bytes, int] = {}
        # pages with ref_count==0 but still holding reusable KV, LRU order
        self.evictable: OrderedDict[int, None] = OrderedDict()
        self.prefix_queries = 0
        self.prefix_hits = 0  # counted in pages
        self.offload_hits = 0  # pages restored from the offload tiers
        # KVOffloadConnector (kvoffload/connector.py): spill evicted pages to
        # host DRAM/disk/remote and restore them on later prefix matches
        self.offload = offload

    # -- allocation ---------------------------------------------------------

    def num_free(self) -> int:
        return len(self.free_list) + len(self.evictable)

    def usage(self) -> float:
        return 1.0 - self.num_free() / self.num_pages

    def allocate(self, n: int) -> Optional[list[int]]:
        if self.num_free() < n:
            return None
        out, spill = [], []
        for _ in range(n):
            if self.free_list:
                pid = self.free_list.pop()
            else:  # evict oldest reusable page
                pid, _ = self.evictable.popitem(last=False)
                info = self.pages[pid]
                if info.hash is not None:
                    if self.offload is not None:  # spill KV before slot reuse
                        spill.append((pid, info.hash))
                    self.hash_to_page.pop(info.hash, None)
                    info.hash = None
            self.pages[pid].ref_count = 1
            out.append(pid)
        if spill:
            # batched: one device fetch for the whole eviction set, not one
            # ~100 ms host<->device round trip per page (connector.save_pages).
            # Over budget, the OLDEST evictions spill — eviction order is
            # free order, i.e. a sequence's HEAD pages first, and a prefix
            # chain can only restore from its head (the tail past the cap
            # recomputes, or re-shares if still in HBM). The rest are
            # dropped + reported evicted so the global KV index stays
            # truthful.
            cap = self.max_io_pages
            if cap and len(spill) > cap:
                dropped = spill[cap:]
                spill = spill[:cap]
                self.offload.report_evict([h for _, h in dropped])
            import time as time_mod

            from production_stack_tpu import tracing

            t_wall, t0 = time_mod.time(), time_mod.perf_counter()
            self.offload.save_pages(spill)
            # spill span under whichever request's admission forced the
            # eviction (scheduler publishes it); decode-growth evictions
            # carry no ambient context and record nothing
            ctx = tracing.current_context()
            if ctx is not None:
                tracing.get_collector().record(
                    "engine.kv_spill", ctx.child(), t_wall,
                    time_mod.perf_counter() - t0, pages=len(spill),
                )
        return out

    def free(self, page_ids: Sequence[int]) -> None:
        for pid in page_ids:
            info = self.pages[pid]
            info.ref_count -= 1
            assert info.ref_count >= 0, f"double free of page {pid}"
            if info.ref_count == 0:
                if info.hash is not None:
                    self.evictable[pid] = None  # keep KV for reuse
                else:
                    self.free_list.append(pid)

    # -- prefix cache -------------------------------------------------------

    def match_prefix(
        self, tokens: Sequence[int], salt: bytes = b""
    ) -> tuple[list[int], int]:
        """Longest cached prefix of `tokens` (page-aligned).

        Returns (shared_page_ids, num_cached_tokens). Increments ref counts of
        the returned pages (caller owns them until `free`).
        """
        hashes = prefix_hashes(tokens, self.page_size, salt)
        self.prefix_queries += max(len(hashes), 1)
        shared: list[int] = []
        for h in hashes:
            pid = self.hash_to_page.get(h)
            if pid is None:
                break
            info = self.pages[pid]
            if info.ref_count == 0:
                self.evictable.pop(pid, None)
            info.ref_count += 1
            shared.append(pid)
        if self.offload is not None:
            shared = self._extend_from_offload(hashes, shared)
        self.prefix_hits += len(shared)
        return shared, len(shared) * self.page_size

    def _extend_from_offload(
        self, hashes: list[bytes], shared: list[int]
    ) -> list[int]:
        """Extend an HBM prefix match from the offload tiers — BATCHED.

        Plans the whole chain extension first (HBM re-shares interleaved with
        tier restores), then restores every needed page through ONE
        host->device upload + scatter per <=64 pages
        (connector.load_pages). The per-page restore this replaces paid a
        full host<->device round trip (~100 ms network-attached) per page —
        an 8k-token history (128 pages) would have taken >10 s to restore.
        """
        # plan the longest contiguous extension: share pages already (back)
        # in HBM, restore tier-resident ones; stop at the first miss
        plan: list[tuple[bytes, Optional[int]]] = []  # (hash, pid | None)
        n_restores = 0
        for h in hashes[len(shared):]:
            pid = self.hash_to_page.get(h)
            if pid is not None:
                # chunk re-appeared in HBM further along the chain (e.g.
                # registered by a later request) — share it, don't restore.
                # Ref it NOW so planning's own allocations can't evict it.
                info = self.pages[pid]
                if info.ref_count == 0:
                    self.evictable.pop(pid, None)
                info.ref_count += 1
                plan.append((h, pid))
            elif self.offload.has(h):
                if self.max_io_pages and n_restores >= self.max_io_pages:
                    # restore budget exhausted: truncate the chain here — on
                    # a slow link the remaining prefix RECOMPUTES faster
                    # than it restores (EngineConfig.kv_offload_max_io_pages).
                    # Checked only when a restore is actually NEEDED: pages
                    # still HBM-resident keep sharing for free above.
                    break
                plan.append((h, None))
                n_restores += 1
            else:
                break
        # allocate slots for every restore; shrink the plan from the tail
        # until the allocation fits (dropping a share un-refs it)
        restore_pids: list[int] = []
        while plan:
            n_restore = sum(1 for _, p in plan if p is None)
            if n_restore == 0:
                break
            got = self.allocate(n_restore)
            if got is not None:
                restore_pids = got
                break
            h, pid = plan.pop()
            if pid is not None:
                self.free([pid])
        n_restore = len(restore_pids)
        restored = 0
        if n_restore:
            import time as time_mod

            from production_stack_tpu import tracing

            t_wall, t0 = time_mod.time(), time_mod.perf_counter()
            restored = self.offload.load_pages(
                list(zip(restore_pids, (h for h, p in plan if p is None)))
            )
            dt = time_mod.perf_counter() - t0
            # restore latency is a first-class phase: histogram always
            # (dashboard phase panels), span when the admission is traced
            tracing.offload_restore_hist.observe(dt)
            ctx = tracing.current_context()
            if ctx is not None:
                tracing.get_collector().record(
                    "engine.kv_restore", ctx.child(), t_wall, dt,
                    pages_planned=n_restore, pages_restored=restored,
                )
        # stitch the final chain: a failed restore truncates it there;
        # shares past the truncation un-ref, unused restore slots free
        ri = 0
        broke = False
        for h, pid in plan:
            if broke:
                if pid is not None:
                    self.free([pid])
            elif pid is not None:
                shared.append(pid)
            elif ri < restored:
                rp = restore_pids[ri]
                ri += 1
                info = self.pages[rp]
                info.hash = h
                self.hash_to_page[h] = rp
                shared.append(rp)
                self.offload_hits += 1
            else:
                broke = True
        if ri < n_restore:
            self.free(restore_pids[ri:])  # unhashed -> back to the free list
        return shared

    def register_filled(
        self, tokens: Sequence[int], page_ids: Sequence[int], salt: bytes = b""
    ) -> None:
        """Record hashes for fully-written pages of a sequence so later
        requests can share them. Called after prefill completes."""
        hashes = prefix_hashes(tokens, self.page_size, salt)
        new: list[bytes] = []
        for h, pid in zip(hashes, page_ids):
            info = self.pages[pid]
            if info.hash is None and h not in self.hash_to_page:
                info.hash = h
                self.hash_to_page[h] = pid
                new.append(h)
        if self.offload is not None and new:
            self.offload.report_admit(new)  # global KV index (kvaware routing)

    def hit_rate(self) -> float:
        return self.prefix_hits / self.prefix_queries if self.prefix_queries else 0.0
