"""Multi-LoRA adapter manager (engine side).

The reference stack reaches LoRA through vLLM's ``--enable-lora`` plus the
engine HTTP endpoints ``/v1/load_lora_adapter`` / ``/v1/unload_lora_adapter``
that the Go ``LoraAdapter`` controller drives
(operator/internal/controller/loraadapter_controller.go:586-616 in
/root/reference). Here the engine owns the implementation:

- Adapters live in slot-stacked device buffers (``models.llama.init_lora_buffers``)
  so a single compiled program serves a batch mixing any loaded adapters
  (batched LoRA, the S-LoRA/punica idea expressed as one gather + two einsums
  that XLA maps onto the MXU).
- ``load()`` reads a PEFT checkpoint directory (``adapter_config.json`` +
  ``adapter_model.safetensors``), maps HF module names to our stacked leaf
  names, pads rank to the configured max, and writes the slot in place on
  device.
- Slot 0 is reserved for the base model and is always all-zero.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

import numpy as np

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

# HF/PEFT module name -> our stacked-weight leaf name
_HF_TO_LEAF = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}
_LEAF_TO_HF = {v: k for k, v in _HF_TO_LEAF.items()}


class LoRAError(ValueError):
    pass


class LoRAManager:
    """Tracks adapter-name -> slot and writes adapter weights into the runner's
    device buffers. Thread-safe: the HTTP side loads/unloads while the engine
    loop reads slots (slot content swaps are atomic device-array updates)."""

    def __init__(self, runner, *, max_loras: int = 4, max_rank: int = 16):
        self.runner = runner
        self.max_loras = max_loras  # concurrent adapters (slot 0 = base, extra)
        self.max_rank = max_rank
        self._lock = threading.Lock()
        # name -> slot (1-based; 0 = base), generation of the current load,
        # and slot -> in-flight request count: the HTTP executor threads
        # load/unload while requests resolve/pin — all under _lock
        self._slots: dict[str, int] = {}  # guarded-by: _lock
        self._gen = 0  # bumped per load: versions the prefix-cache salt
        self._salt_gen: dict[str, int] = {}  # guarded-by: _lock
        self._refs: dict[int, int] = {}  # guarded-by: _lock

    # -- queries -------------------------------------------------------------

    def list_adapters(self) -> list[str]:
        with self._lock:
            return sorted(self._slots)

    def slot_for(self, name: Optional[str]) -> int:
        """Resolve a request's model name to an adapter slot (0 = base)."""
        if not name:
            return 0
        with self._lock:
            return self._slots.get(name, 0)

    def is_adapter(self, name: str) -> bool:
        with self._lock:
            return name in self._slots

    def acquire(self, name: str) -> tuple[int, bytes]:
        """Atomically resolve an adapter for a request and pin its slot
        (refcounted) so unload cannot clear or re-target it while the request
        is in flight. Pair with release(). Single-lock atomicity closes the
        resolve-then-increment race a separate counter would have."""
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                raise LoRAError(f"LoRA adapter {name!r} is not loaded")
            self._refs[slot] = self._refs.get(slot, 0) + 1
            gen = self._salt_gen[name]
            return slot, f"lora:{name}:{gen}".encode()

    def release(self, slot: int) -> None:
        with self._lock:
            n = self._refs.get(slot, 0) - 1
            if n > 0:
                self._refs[slot] = n
            else:
                self._refs.pop(slot, None)

    def has_free_slot(self) -> bool:
        with self._lock:
            return len(self._slots) < self.max_loras

    def cache_salt(self, name: str) -> bytes:
        """Prefix-cache salt for an adapter. Versioned per load(): reloading a
        retrained checkpoint under the same name gets a fresh salt, so pages
        cached under the old weights can never match (they age out via LRU)."""
        with self._lock:
            gen = self._salt_gen.get(name)
        return b"" if gen is None else f"lora:{name}:{gen}".encode()

    # -- load / unload -------------------------------------------------------

    def load(self, name: str, path: str) -> int:
        """Parse + load a PEFT adapter directory into a free slot."""
        tensors, scale = self.read_checkpoint(path)
        return self.load_parsed(name, tensors, scale)

    def load_parsed(self, name: str, tensors: dict, scale: float) -> int:
        """Write pre-parsed adapter weights into a free slot; returns the slot.

        Device-buffer writes must be serialized with the engine step loop —
        LLMEngine parses the checkpoint on the HTTP executor thread
        (read_checkpoint) and routes only this device write through its inbox
        so it executes on the device thread between steps (no concurrent
        donation of live buffers, no disk I/O under the lock)."""
        with self._lock:
            if name in self._slots:
                raise LoRAError(f"adapter {name!r} is already loaded")
            used = set(self._slots.values())
            # slots 1..max_loras inclusive: max_loras counts *adapters* (slot 0
            # is the base model and comes on top, matching vLLM's --max-loras)
            free = [s for s in range(1, self.max_loras + 1) if s not in used]
            if not free:
                raise LoRAError(
                    f"no free LoRA slots (max_loras={self.max_loras}, "
                    f"loaded={sorted(self._slots)})"
                )
            slot = free[0]
            self.runner.set_lora_slot(slot, tensors, scale)
            self._gen += 1
            self._salt_gen[name] = self._gen
            self._slots[name] = slot
            logger.info("loaded LoRA adapter %r into slot %d", name, slot)
            return slot

    def unload(self, name: str, in_use: bool = False) -> None:
        """Unload an adapter. Refuses while requests hold the slot (acquire()
        refs, checked under the same lock) or when the caller supplies an
        extra in-use signal (e.g. a scheduler scan)."""
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                raise LoRAError(f"adapter {name!r} is not loaded")
            if in_use or self._refs.get(slot, 0) > 0:
                raise LoRAError(
                    f"adapter {name!r} has in-flight requests; retry when drained"
                )
            del self._slots[name]
            self._salt_gen.pop(name, None)
            self.runner.clear_lora_slot(slot)
            logger.info("unloaded LoRA adapter %r (slot %d)", name, slot)

    # -- PEFT checkpoint parsing --------------------------------------------

    def read_checkpoint(self, path: str) -> tuple[dict, float]:
        """Read adapter_config.json + adapter_model.safetensors into stacked
        per-target arrays ``{a_<t>: [L, in, R], b_<t>: [L, R, out]}``."""
        cfg_path = os.path.join(path, "adapter_config.json")
        if not os.path.isfile(cfg_path):
            raise LoRAError(f"no adapter_config.json in {path}")
        with open(cfg_path) as f:
            acfg = json.load(f)
        r = int(acfg.get("r", 8))
        alpha = float(acfg.get("lora_alpha", r))
        if r > self.max_rank:
            raise LoRAError(
                f"adapter rank {r} exceeds max_lora_rank {self.max_rank}"
            )
        st_path = os.path.join(path, "adapter_model.safetensors")
        if not os.path.isfile(st_path):
            raise LoRAError(f"no adapter_model.safetensors in {path}")
        from safetensors import safe_open

        raw: dict[str, np.ndarray] = {}
        with safe_open(st_path, framework="np") as f:
            for key in f.keys():
                raw[key] = f.get_tensor(key)

        cfg = self.runner.cfg
        targets = self.runner.lora_targets
        L, R = cfg.num_layers, self.max_rank
        from production_stack_tpu.models.llama import lora_dims

        dims = lora_dims(cfg)
        # refuse adapters that target modules we are not applying: silently
        # dropping trained deltas would serve a different model than trained
        enabled_hf = {_LEAF_TO_HF[t] for t in targets}
        in_ckpt = set()
        for key in raw:
            if key.endswith(".lora_A.weight"):
                in_ckpt.add(key.split(".")[-3])
        extra = in_ckpt - enabled_hf
        if extra:
            raise LoRAError(
                f"adapter targets {sorted(extra)} but only {sorted(enabled_hf)} "
                f"are enabled (--lora-target-modules); refusing partial application"
            )
        out: dict[str, np.ndarray] = {}
        present = set()
        for t in targets:
            din, dout = dims[t]
            a = np.zeros((L, din, R), np.float32)
            b = np.zeros((L, R, dout), np.float32)
            hf = _LEAF_TO_HF[t]
            for layer in range(L):
                ka = _find_tensor(raw, layer, hf, "lora_A")
                kb = _find_tensor(raw, layer, hf, "lora_B")
                if ka is None or kb is None:
                    continue
                present.add(t)
                wa = raw[ka]  # PEFT stores lora_A as [r, in], lora_B as [out, r]
                wb = raw[kb]
                if wa.shape != (r, din) or wb.shape != (dout, r):
                    raise LoRAError(
                        f"layer {layer} {hf}: expected A {(r, din)} / B {(dout, r)}, "
                        f"got {wa.shape} / {wb.shape}"
                    )
                a[layer, :, :r] = wa.T
                b[layer, :r, :] = wb.T
            out["a_" + t] = a
            out["b_" + t] = b
        if not present:
            raise LoRAError(
                f"adapter in {path} targets none of the enabled modules "
                f"{[ _LEAF_TO_HF[t] for t in targets ]}"
            )
        return out, alpha / r


def _find_tensor(raw: dict, layer: int, hf_name: str, ab: str) -> Optional[str]:
    """Locate a PEFT tensor key tolerating prefix variants
    (``base_model.model.model.layers.N...`` vs ``model.layers.N...``)."""
    needle = f".layers.{layer}."
    suffix_attn = f".self_attn.{hf_name}.{ab}.weight"
    suffix_mlp = f".mlp.{hf_name}.{ab}.weight"
    for key in raw:
        if needle in key and (key.endswith(suffix_attn) or key.endswith(suffix_mlp)):
            return key
    return None


def save_peft_adapter(path: str, cfg, rank: int, alpha: float, tensors: dict) -> None:
    """Write a PEFT-format adapter directory (test fixture / round-trip tool).

    ``tensors`` maps leaf target name -> (A [L, r, in], B [L, out, r]) in the
    PEFT orientation.
    """
    os.makedirs(path, exist_ok=True)
    target_modules = sorted(_LEAF_TO_HF[t] for t in tensors)
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump(
            {
                "peft_type": "LORA",
                "r": rank,
                "lora_alpha": alpha,
                "target_modules": target_modules,
                "task_type": "CAUSAL_LM",
            },
            f,
        )
    flat: dict[str, np.ndarray] = {}
    for t, (a, b) in tensors.items():
        hf = _LEAF_TO_HF[t]
        group = "mlp" if t in ("w_gate", "w_up", "w_down") else "self_attn"
        for layer in range(a.shape[0]):
            base = f"base_model.model.model.layers.{layer}.{group}.{hf}"
            flat[f"{base}.lora_A.weight"] = np.asarray(a[layer], np.float32)
            flat[f"{base}.lora_B.weight"] = np.asarray(b[layer], np.float32)
    from safetensors.numpy import save_file

    save_file(flat, os.path.join(path, "adapter_model.safetensors"))
