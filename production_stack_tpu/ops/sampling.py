"""Token sampling inside jit — greedy / temperature / top-k / top-p, vectorized
over the batch with *per-request* parameters (the OpenAI API allows each request
its own temperature/top_p), all with static shapes.

TPU note: a full-vocab sort per step is wasteful on the VPU; instead we take the
top ``CANDIDATES`` logits with ``lax.top_k`` (a fused TPU primitive) and apply
top-k / top-p filtering within that candidate set. With CANDIDATES=64 the
truncated tail mass at typical temperatures is far below 1e-4; greedy decoding
uses a full argmax and is exact. (vLLM applies top-p over the full vocab; the
candidate truncation is this engine's documented deviation, chosen for TPU
throughput.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

CANDIDATES = 64


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Sample next token ids.

    Args:
      logits:      [B, V] float logits.
      key:         PRNG key for this step (categorical draws are independent
                   per batch row).
      temperature: [B] float; 0 => greedy for that row.
      top_k:       [B] int; 0 or >=CANDIDATES => no top-k truncation.
      top_p:       [B] float in (0, 1]; 1 => no nucleus truncation.

    Returns [B] int32 token ids.
    """
    B, V = logits.shape
    n_cand = min(CANDIDATES, V)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    cand_logits, cand_ids = lax.top_k(logits.astype(jnp.float32), n_cand)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = cand_logits / temp

    rank = jnp.arange(n_cand)[None, :]
    k = jnp.where(top_k <= 0, n_cand, jnp.minimum(top_k, n_cand))[:, None]
    scaled = jnp.where(rank < k, scaled, -jnp.inf)

    probs = jax.nn.softmax(scaled, axis=-1)
    cumsum = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose *preceding* cumulative mass is < top_p (always keep rank 0).
    keep = (cumsum - probs) < top_p[:, None]
    scaled = jnp.where(keep, scaled, -jnp.inf)

    sampled_rank = jax.random.categorical(key, scaled, axis=-1)
    sampled_ids = jnp.take_along_axis(cand_ids, sampled_rank[:, None], axis=1)[:, 0]

    use_greedy = temperature <= 0.0
    return jnp.where(use_greedy, greedy_ids, sampled_ids.astype(jnp.int32))


# OpenAI caps top_logprobs at 20; vLLM allows 20 too. Static so shapes stay
# fixed regardless of each request's requested count (host slices).
TOP_LOGPROBS = 20


def sample_with_logprobs(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    sample_from: jnp.ndarray | None = None,
):
    """``sample`` plus logprob reporting (OpenAI/vLLM semantics: logprobs of
    the RAW distribution — log-softmax of unscaled ``logits`` — independent of
    temperature/top-k/top-p truncation and of penalties). ``sample_from``
    optionally substitutes a penalty-adjusted distribution for the draw.

    Returns (ids [B] int32, chosen_logprob [B] f32,
             top_ids [B, TOP_LOGPROBS] int32, top_logprobs [B, TOP_LOGPROBS] f32).
    """
    ids = sample(
        logits if sample_from is None else sample_from,
        key, temperature, top_k, top_p,
    )
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1, keepdims=True)  # [B, 1]
    logprobs = lf - lse
    chosen = jnp.take_along_axis(logprobs, ids[:, None].astype(jnp.int32), axis=1)[:, 0]
    top_lp, top_ids = lax.top_k(logprobs, min(TOP_LOGPROBS, logits.shape[1]))
    return ids, chosen, top_ids.astype(jnp.int32), top_lp


def apply_penalties(
    logits: jnp.ndarray,      # [B, V] f32
    history: jnp.ndarray,     # [B, H] int32 token ids (prompt + output), 0-padded
    hist_len: jnp.ndarray,    # [B] int32 valid prefix of history
    prompt_len: jnp.ndarray,  # [B] int32 prompt portion (output starts here)
    presence: jnp.ndarray,    # [B] f32 (0 = off)
    frequency: jnp.ndarray,   # [B] f32 (0 = off)
    repetition: jnp.ndarray,  # [B] f32 (1 = off)
) -> jnp.ndarray:
    """OpenAI presence/frequency penalties (over generated tokens) and vLLM
    repetition penalty (over prompt + generated), vectorized per row.

    presence/frequency: logits -= presence * 1[count>0] + frequency * count,
    counting OUTPUT tokens only (vLLM semantics). repetition: seen tokens'
    positive logits divide by r, negative multiply by r, counting prompt AND
    output. All counts come from the position-indexed history buffer, so the
    same code path serves single steps and fused bursts.
    """
    B, V = logits.shape
    H = history.shape[1]
    idx = jnp.arange(H, dtype=jnp.int32)[None, :]
    valid = idx < hist_len[:, None]
    out_part = valid & (idx >= prompt_len[:, None])
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    # OOB sentinel V drops masked slots (mode="drop")
    all_ids = jnp.where(valid, history, V)
    out_ids = jnp.where(out_part, history, V)
    zeros = jnp.zeros((B, V), jnp.float32)
    all_counts = zeros.at[rows, all_ids].add(1.0, mode="drop")
    out_counts = zeros.at[rows, out_ids].add(1.0, mode="drop")

    # vLLM order: repetition applies to the RAW logits first, then presence/
    # frequency subtract — so a positive logit dragged negative by the
    # frequency term still divides (not multiplies) by r.
    seen = all_counts > 0
    rep = jnp.maximum(repetition, 1e-6)[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen, penalized, logits)
    logits = logits - frequency[:, None] * out_counts
    logits = logits - presence[:, None] * (out_counts > 0)
    return logits


def apply_logit_bias(
    logits: jnp.ndarray,     # [B, V] f32
    bias_ids: jnp.ndarray,   # [B, K] int32 token ids; >= V = unused slot
    bias_vals: jnp.ndarray,  # [B, K] f32 additive biases
) -> jnp.ndarray:
    """OpenAI logit_bias: add per-row sparse biases to the sampling
    distribution. Unused slots carry an out-of-range id and drop."""
    B = logits.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    return logits.at[rows, bias_ids].add(bias_vals, mode="drop")
