"""Token sampling inside jit — greedy / temperature / top-k / top-p, vectorized
over the batch with *per-request* parameters (the OpenAI API allows each request
its own temperature/top_p), all with static shapes.

TPU note: a full-vocab sort per step is wasteful on the VPU; instead we take the
top ``CANDIDATES`` logits with ``lax.top_k`` (a fused TPU primitive) and apply
top-k / top-p filtering within that candidate set. With CANDIDATES=64 the
truncated tail mass at typical temperatures is far below 1e-4; greedy decoding
uses a full argmax and is exact. (vLLM applies top-p over the full vocab; the
candidate truncation is this engine's documented deviation, chosen for TPU
throughput.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

CANDIDATES = 64


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Sample next token ids.

    Args:
      logits:      [B, V] float logits.
      key:         PRNG key for this step (categorical draws are independent
                   per batch row).
      temperature: [B] float; 0 => greedy for that row.
      top_k:       [B] int; 0 or >=CANDIDATES => no top-k truncation.
      top_p:       [B] float in (0, 1]; 1 => no nucleus truncation.

    Returns [B] int32 token ids.
    """
    B, V = logits.shape
    n_cand = min(CANDIDATES, V)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    cand_logits, cand_ids = lax.top_k(logits.astype(jnp.float32), n_cand)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = cand_logits / temp

    rank = jnp.arange(n_cand)[None, :]
    k = jnp.where(top_k <= 0, n_cand, jnp.minimum(top_k, n_cand))[:, None]
    scaled = jnp.where(rank < k, scaled, -jnp.inf)

    probs = jax.nn.softmax(scaled, axis=-1)
    cumsum = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose *preceding* cumulative mass is < top_p (always keep rank 0).
    keep = (cumsum - probs) < top_p[:, None]
    scaled = jnp.where(keep, scaled, -jnp.inf)

    sampled_rank = jax.random.categorical(key, scaled, axis=-1)
    sampled_ids = jnp.take_along_axis(cand_ids, sampled_rank[:, None], axis=1)[:, 0]

    use_greedy = temperature <= 0.0
    return jnp.where(use_greedy, greedy_ids, sampled_ids.astype(jnp.int32))
