"""Rotary position embeddings (RoPE), including Llama-3 frequency scaling.

Computed on the fly from integer positions (no precomputed cos/sin table kept in
HBM): for serving, positions are ragged per sequence and a gather from a table is
the same cost as recomputing sin/cos on the VPU, while recomputation avoids a
max_position-sized table and keeps shapes static under jit.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class RopeScaling:
    """Llama-3 style rope scaling (`rope_type: llama3` in HF configs)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192


def _inv_freq(head_dim: int, theta: float, scaling: Optional[RopeScaling]) -> jnp.ndarray:
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponent)
    if scaling is None:
        return inv_freq
    # Llama-3 NTK-by-parts scaling.
    low_wavelen = scaling.original_max_position / scaling.low_freq_factor
    high_wavelen = scaling.original_max_position / scaling.high_freq_factor
    wavelen = 2.0 * math.pi / inv_freq
    scaled = inv_freq / scaling.factor
    smooth = (scaling.original_max_position / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor
    )
    smooth = jnp.clip(smooth, 0.0, 1.0)
    mid = (1.0 - smooth) * scaled + smooth * inv_freq
    return jnp.where(wavelen > low_wavelen, scaled, jnp.where(wavelen < high_wavelen, inv_freq, mid))


def rope_cos_sin(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float = 10000.0,
    scaling: Optional[RopeScaling] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin of shape positions.shape + (head_dim // 2,), float32."""
    inv_freq = _inv_freq(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate pairs (x[..., :d/2], x[..., d/2:]) — HF 'neox' convention used by
    Llama/Qwen. x: [..., heads, head_dim]; cos/sin: [..., head_dim//2] broadcast
    over the heads axis."""
    dtype = x.dtype
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    c = cos[..., None, :]
    s = sin[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
