"""Pallas TPU kernel: flash prefill attention over paged KV.

Why (round-5 measurement): the XLA chunked-prefill path
(ops/attention.py flash_attention after gather_kv_pages) materializes a
[B, S, KH, D] gather of the page pool per layer AND runs its online-softmax
as a 32-step lax.scan at 16k context — measured ~93 ms per 1k-token chunk at
16k context on v5e (vs ~25 ms at 1k context), i.e. the attention term runs
at well under 20% MFU right when it dominates (2.2 TFLOP per chunk at 16k).
This kernel streams pages HBM->VMEM exactly once via scalar-prefetch page
indirection (same trick as paged_attention.py's decode kernel), keeps the
(m, l, acc) flash state in VMEM scratch across a query block's KV sweep, and
folds the chunk's own in-register K/V (write-after-attend mode: the pool is
stale for the current chunk) as a final block — no pool gather, no scan.

Masking model mirrors ops/attention.stale_kv_positions: paged slot s holds
absolute position s and is valid while s < paged_end_b = kv_lens[b] -
cur_lens[b] (later slots are stale; the chunk's K/V ride in-register), so
every valid paged slot is causally visible to every chunk query (chunk
positions all >= chunk start) and only the validity bound is needed; chunk
entry j at positions[b, j] is visible to query t iff positions[b, j] >= 0
and positions[b, j] <= positions[b, t]. Padded rows (positions -1) see
nothing and emit zeros.

Equivalent role in the reference: vLLM's CUDA prefill (flash-attn) kernels
inside the engine image (/root/reference helm/templates/
deployment-vllm-multi.yaml:128-141); tests assert equivalence against the
XLA oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _prefill_kernel(
    # scalar prefetch
    pt_ref,      # [B, max_pages] int32 page table (drives kv block fetch)
    lens_ref,    # [B] int32 kv lengths (chunk end)
    cl_ref,      # [B] int32 chunk sizes (in-register entries)
    win_ref,     # [1] int32 window (huge = full causal)
    layer_ref,   # [1] int32 layer into stacked pools
    # blocks
    q_ref,       # [1, TQ, NH, D]
    pos_ref,     # [1, TQ] int32 query positions (-1 pad)
    *refs,       # N x (k_ref, v_ref) [1, 1, page, KH, D], k_cur, v_cur
                 # ([1, C, KH, D]), cpos_ref [1, C], o_ref, qg/m/l/acc scratch
    sm_scale: float,
    kv_heads: int,
    logit_softcap: float | None,
    pages_per_block: int,
):
    N = pages_per_block
    kv_refs = refs[: 2 * N]
    (k_cur_ref, v_cur_ref, cpos_ref, o_ref,
     qg_ref, m_ref, l_ref, acc_ref) = refs[2 * N:]
    b = pl.program_id(0)
    p = pl.program_id(2)
    page_size = kv_refs[0].shape[2]
    TQ, NH, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    KH = kv_heads
    G = NH // KH

    @pl.when(p == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        # queries split per GQA group into scratch: group g's heads are
        # h = kh*G + g, so q4[:, :, g] is the [TQ, KH, D] slice batched over
        # KH. Row packing (one [KH, G*TQ, D] matmul) hits Mosaic reshape
        # limits (minor-dim collapses are unsupported shape casts); scratch
        # lets the fold below index groups DYNAMICALLY from a fori_loop.
        q4 = (
            q_ref[0] * jnp.asarray(sm_scale, q_ref.dtype)
        ).reshape(TQ, KH, G, D)
        for g in range(G):
            qg_ref[g] = q4[:, :, g].transpose(1, 0, 2)  # [KH, TQ, D]

    paged_end = lens_ref[b] - cl_ref[b]
    pos_q = pos_ref[0]  # [TQ]

    def fold(k, v, kv_pos, valid):
        """One online-softmax update; k/v [KH, S, D], kv_pos/valid [S].

        The GQA groups run under a fori_loop, NOT a Python loop: every
        unrolled fold gets its own scoped-vmem stack for the [KH, TQ, S]
        f32 score temporaries (Mosaic does not reuse stacks across unrolled
        statements — measured 4 pages x 4 groups unrolled at 26 MB vs the
        16 MB budget), while a loop body compiles once and reuses one stack.
        Inputs stay in their own dtype (bf16 in production: MXU-native, and
        f32 copies of q/k/v doubled the stack).
        """
        vis = (
            valid[None, None, :]
            & (kv_pos[None, None, :] <= pos_q[None, :, None])
            & (pos_q[None, :, None] >= 0)
            & (kv_pos[None, None, :] > pos_q[None, :, None] - win_ref[0])
        )  # [1, TQ, S]

        def gbody(g, carry):
            s = lax.dot_general(
                qg_ref[g], k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [KH, TQ, S]
            if logit_softcap is not None:
                s = logit_softcap * jnp.tanh(s / logit_softcap)
            s = jnp.where(vis, s, NEG_INF)
            m_prev, l_prev = m_ref[g], l_ref[g]
            m_new = jnp.maximum(m_prev, s.max(axis=-1))
            alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            pij = jnp.exp(s - m_new[..., None])
            pij = jnp.where(vis, pij, 0.0)
            m_ref[g] = m_new
            l_ref[g] = l_prev * alpha + pij.sum(axis=-1)
            pv = lax.dot_general(
                pij.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [KH, TQ, D]; bf16 pij on the MXU, f32 accumulate
            acc_ref[g] = acc_ref[g] * alpha[..., None] + pv
            return carry

        lax.fori_loop(0, G, gbody, 0)

    for i in range(N):
        start = (p * N + i) * page_size

        @pl.when(start < paged_end)
        def _(k_ref=kv_refs[2 * i], v_ref=kv_refs[2 * i + 1], start=start):
            k = k_ref[0, 0].transpose(1, 0, 2)  # [KH, page, D], pool dtype
            v = v_ref[0, 0].transpose(1, 0, 2)
            idx = start + lax.iota(jnp.int32, page_size)
            # paged slot position == slot index; causal vs chunk queries is
            # automatic (slot < paged_end <= every valid query position)
            fold(k, v, idx, idx < paged_end)

    @pl.when(p == pl.num_programs(2) - 1)
    def _():
        # fold the chunk's own K/V (stale in the pool) in sub-blocks under a
        # fori_loop (same stack-reuse point as the groups; one [KH, TQ, C]
        # f32 score tensor for a 1k chunk also blew the budget on size)
        C = k_cur_ref.shape[1]
        CB = min(128, C)

        def cbody(ci, carry):
            c0 = ci * CB
            kc = k_cur_ref[0, pl.dslice(c0, CB)].transpose(1, 0, 2)
            vc = v_cur_ref[0, pl.dslice(c0, CB)].transpose(1, 0, 2)
            cpos = cpos_ref[0, pl.dslice(c0, CB)]  # entry positions (-1 pad)
            fold(kc, vc, cpos, cpos >= 0)
            return carry

        lax.fori_loop(0, C // CB, cbody, 0)
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)[..., None]
        # [G, KH, TQ, D] -> [TQ, NH, D] with h = kh*G + g: stack heads as
        # (KH, G) then collapse — all major-dim moves
        out = out.transpose(2, 1, 0, 3).reshape(TQ, NH, D)
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "sm_scale", "logit_softcap", "interpret", "pages_per_block", "q_block"
    ),
)
def ragged_paged_attention_prefill(
    q: jnp.ndarray,          # [B, T, NH, D] chunk queries
    k_pages: jnp.ndarray,    # [P, page, KH, D] or [L, P, page, KH, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray, # [B, max_pages] int32
    positions: jnp.ndarray,  # [B, T] int32 absolute query positions, -1 pad
    kv_lens: jnp.ndarray,    # [B] int32 chunk-end lengths
    k_cur: jnp.ndarray,      # [B, T, KH, D] the chunk's K/V (post-write mode)
    v_cur: jnp.ndarray,
    cur_lens: jnp.ndarray,   # [B] valid chunk entries
    window=None,
    *,
    sm_scale: float | None = None,
    logit_softcap: float | None = None,
    interpret: bool = False,
    pages_per_block: int | None = None,
    q_block: int = 128,
    layer: jnp.ndarray | int | None = None,
) -> jnp.ndarray:
    """Chunked-prefill attention over paged KV + in-register chunk K/V.

    Write-after-attend contract (ops/attention.stale_kv_positions): pool
    slots at positions >= kv_lens - cur_lens are stale — the chunk's K/V
    arrive in ``k_cur/v_cur`` and fold in at the end of each query block's
    KV sweep. Returns [B, T, NH, D] in q.dtype; matches the XLA oracle
    (flash_attention with kv_positions) — tests assert equivalence.
    """
    B, T, NH, D = q.shape
    if k_pages.ndim == 4:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        layer = 0
    _, _, page_size, KH, _ = k_pages.shape
    max_pages = page_table.shape[1]
    G = NH // KH
    scale = sm_scale if sm_scale is not None else D**-0.5
    if pages_per_block is None:
        # ONE page per grid cell: unlike decode (one token of compute per
        # cell, grouping essential), a prefill cell does TQ x page x NH work
        # — plenty to hide the per-cell pipeline overhead — and every
        # unrolled page adds its own scoped-vmem stack for the f32 score
        # temporaries (measured: N=4 x G=4 blew the 16 MB budget)
        pages_per_block = max(1, min(128 // page_size, max_pages))
    N = max(1, min(pages_per_block, max_pages))
    n_pb = -(-max_pages // N)
    TQ = min(q_block, T)
    n_qb = -(-T // TQ)
    if n_qb * TQ != T:
        pad = n_qb * TQ - T
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        positions = jnp.pad(positions, ((0, 0), (0, pad)), constant_values=-1)
    # pad the chunk operands to a whole number of CB=128 fold sub-blocks
    # (padded entries carry cpos=-1 -> invisible); without this the kernel's
    # fori over C // CB would silently drop the tail of a non-multiple chunk
    CB = 128
    if T % CB:
        cpad = CB - T % CB
        k_cur = jnp.pad(k_cur, ((0, 0), (0, cpad), (0, 0), (0, 0)))
        v_cur = jnp.pad(v_cur, ((0, 0), (0, cpad), (0, 0), (0, 0)))
    win = (
        jnp.full((1,), 2**30, jnp.int32)
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)
    cl = jnp.asarray(cur_lens, jnp.int32)
    Cp = k_cur.shape[1]  # CB-padded chunk length
    # chunk entry positions: entry j sits at positions[b, j] (valid j <
    # cur_lens); padded entries (incl. the CB-alignment tail) carry -1 and
    # are invisible to the fold
    cpos = jnp.full((B, Cp), -1, jnp.int32)
    cpos = cpos.at[:, :T].set(
        jnp.where(
            (lax.broadcasted_iota(jnp.int32, (B, T), 1) < cl[:, None])
            & (positions[:, :T] >= 0),
            positions[:, :T],
            -1,
        )
    )

    def kv_index(i):
        def index(b, qb, p, pt, lens, _cl, w, l):
            return (
                l[0],
                pt[b, jnp.minimum(p * N + i, max_pages - 1)],
                0, 0, 0,
            )

        return index

    qrow = lambda b, qb, p, *refs: (b, qb, 0, 0)
    prow = lambda b, qb, p, *refs: (b, qb)
    crow = lambda b, qb, p, *refs: (b, 0, 0, 0)
    crow2 = lambda b, qb, p, *refs: (b, 0)
    in_specs = [
        pl.BlockSpec((1, TQ, NH, D), qrow),
        pl.BlockSpec((1, TQ), prow),
    ]
    operands = [q, positions]
    for i in range(N):
        in_specs += [
            pl.BlockSpec((1, 1, page_size, KH, D), kv_index(i)),
            pl.BlockSpec((1, 1, page_size, KH, D), kv_index(i)),
        ]
        operands += [k_pages, v_pages]
    in_specs += [
        pl.BlockSpec((1, Cp, KH, D), crow),
        pl.BlockSpec((1, Cp, KH, D), crow),
        pl.BlockSpec((1, Cp), crow2),
    ]
    operands += [k_cur, v_cur, cpos]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B, n_qb, n_pb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, TQ, NH, D), qrow),
        scratch_shapes=[
            pltpu.VMEM((G, KH, TQ, D), q.dtype),     # per-group queries
            pltpu.VMEM((G, KH, TQ), jnp.float32),
            pltpu.VMEM((G, KH, TQ), jnp.float32),
            pltpu.VMEM((G, KH, TQ, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, sm_scale=scale, kv_heads=KH,
        logit_softcap=logit_softcap, pages_per_block=N,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_qb * TQ, NH, D), q.dtype),
        interpret=interpret,
        # the default 16 MB scoped-vmem budget is a fraction of v5e's
        # physical VMEM; the f32 score temporaries of a TQ=128 cell need
        # more headroom than decode-sized cells
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * T * NH * D * (max_pages * page_size + T),
            bytes_accessed=(
                2 * max_pages * page_size * KH * D * 2 * B
                + 2 * B * T * (NH + 2 * KH) * D
            ),
            transcendentals=B * NH * T * (max_pages * page_size + T),
        ),
    )(
        page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), cl, win,
        lyr, *operands,
    )
    return out[:, :T]
