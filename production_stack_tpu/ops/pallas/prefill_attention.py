"""Pallas TPU kernel: ragged flash prefill over paged KV (v2) + fused
paged-KV write.

Why v2 (BENCH_r05): chunked prefill throughput went BACKWARDS with context
— 9,788 tok/s at 16k fell to 7,158 at 32k — because the v1 kernel kept the
decode-v1 memory structure the decode kernel already abandoned (PR 3):

1. **Dense grid.** v1 ran grid = (B, n_qb, n_page_blocks) over the page
   BUCKET: a 1k-token history in a 32k bucket still executed ~500 dead
   (query-block x kv-block) cells whose BlockSpec fetches refetched the
   last page. v2 derives each (sequence, query-block)'s LIVE kv-block count
   from ``kv_lens``/``cur_lens`` (and the sliding window, per query block)
   on the host side, packs live cells into a 1D grid, and pads with no-op
   cells that alias the last live cell — prefill cost scales with each
   sequence's REAL history, so mixed 1k/16k batches cost the sum of their
   real work, and a 32k prompt's later chunks pay for 32k once, not
   bucket x chunks.

2. **Page-granular matmuls.** v1 fetched N pages per cell as N separate
   BlockSpec inputs and folded each page separately: a 64-slot score matmul
   fragments the MXU (measured XLA-parity on v5e — the kernel's whole
   advantage vanished into per-page overhead). v2 leaves the pools in HBM
   (``memory_space=ANY``) and drives a manually multi-buffered VMEM ring of
   page copies (``pltpu.make_async_copy``, ``prefill_prefetch_pages``
   deep): N pages land CONTIGUOUSLY in a ring slot and fold as ONE wide
   [KH, TQ, N*page] matmul — the "contiguous-KV variant" the v1 notes
   called the path to a win. Copies stay in flight across cell boundaries,
   so the HBM pipeline never drains between cells.

3. **Fused paged-KV write.** The chunk's own K/V used to ride the layer
   scan as stacked outputs and get committed by a separate post-scan
   scatter (``write_kv_pages_all_layers``): write the stack, read it back,
   scatter into the pool — 3 HBM traversals of the chunk's KV per step.
   With ``fused_write=True`` the kernel writes the chunk's K/V into its
   pool pages directly from VMEM (the pools are aliased input->output), so
   the chunk's KV crosses HBM once. Interior pages are single page-sized
   DMAs; a partial head/tail page (unaligned chunk start, or a chunk end
   mid-page) is read-modify-written so untouched slots keep their exact
   old bytes — tests assert the pool is bit-identical to the scatter path.

Masking model is unchanged from v1 (ops/attention.stale_kv_positions):
paged slot s holds absolute position s and is valid while s < paged_end =
kv_lens[b] - cur_lens[b]; the chunk's K/V ride in-register and fold at each
query block's last cell. Fused-write contract (and the causal block-skip):
valid chunk entries are CONTIGUOUS — entry j sits at position paged_end + j
— which is how the engine's scheduler builds every prefill chunk
(scheduler._plan_prefill). Upper-triangle chunk sub-blocks (entries no
query in the block can see) are skipped by a dynamic loop bound, so they
cost nothing.

Equivalent role in the reference: vLLM's CUDA prefill (flash-attn) kernels
inside the engine image; PAPERS "Ragged Paged Attention" is the direct
blueprint. Tests assert equivalence against the XLA oracle
(tests/test_pallas_prefill.py); scripts/profile_prefill.py measures the
achieved page-streaming HBM GB/s and the ragged-scaling property on chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_FOLD_BLOCK = 128  # chunk-fold sub-block (CB): one score tensor's S extent


def _prefill_kernel(
    # scalar prefetch
    pt_ref,      # [B, max_pages] int32 page table
    lens_ref,    # [B] int32 kv lengths (chunk end)
    cl_ref,      # [B] int32 chunk sizes (valid in-register entries)
    win_ref,     # [1] int32 window (huge = full causal)
    layer_ref,   # [1] int32 layer into stacked pools
    seq_ref,     # [NC] packed cell -> batch row
    qb_ref,      # [NC] packed cell -> query block
    blk_ref,     # [NC] packed cell -> kv block within the (b, qb) live range
    cnt_ref,     # [B*n_qb] live cell count per (b, qb) (>= 1)
    lopg_ref,    # [B*n_qb] first live page (window start) per (b, qb)
    livepg_ref,  # [B*n_qb] live page count per (b, qb) — the packing's
                 # source of truth; the kernel must never re-derive it
    total_ref,   # [1] total live cells
    # inputs
    q_ref,       # [1, TQ, NH, D] (current (b, qb) block)
    pos_ref,     # [1, TQ] int32 query positions (-1 pad)
    kp_hbm,      # [L, P, page, KH, D], memory_space=ANY (stays in HBM)
    vp_hbm,
    kc_ref,      # [1, Cw, KH, D] chunk K/V, front-padded by fp_pad slots
    vc_ref,
    cpos_ref,    # [1, Cw] chunk entry positions (-1 pad)
    *refs,       # [ks_ref, vs_ref (quantized: [1, P, KH] f32 scale slabs),]
                 # o_ref [, kp_out, vp_out [, o_ksc, o_vsc]], then scratch
                 # (see wrapper)
    sm_scale: float,
    kv_heads: int,
    logit_softcap: float | None,
    pages_per_block: int,
    ring_blocks: int,
    n_qb: int,
    fused_write: bool,
    fp_pad: int,
    max_write_pages: int,
    quantized: bool = False,
):
    N = pages_per_block
    RB = ring_blocks
    i0 = 0
    if quantized:
        # int8 pools (ops/quant.py contract): the current layer's [P, KH]
        # scale slabs are VMEM-resident (constant index map, fetched once);
        # each cell's N ring pages dequantize right before the wide fold,
        # and the fused write QUANTIZES the chunk in-kernel so fp chunk KV
        # never crosses HBM either
        ks_ref, vs_ref = refs[0], refs[1]
        i0 = 2
    o_ksc = o_vsc = None
    if fused_write and quantized:
        (o_ref, kp_out, vp_out, o_ksc, o_vsc, k_buf, v_buf, ksem, vsem,
         wk_sem, wv_sem, rk_sem, rv_sem, wbuf_k, wbuf_v,
         qg_ref, m_ref, l_ref, acc_ref) = refs[i0:]
        kp_src, vp_src = kp_out, vp_out  # aliased with kp_hbm/vp_hbm
    elif fused_write:
        (o_ref, kp_out, vp_out, k_buf, v_buf, ksem, vsem,
         wk_sem, wv_sem, rk_sem, rv_sem, wbuf_k, wbuf_v,
         qg_ref, m_ref, l_ref, acc_ref) = refs[i0:]
        kp_src, vp_src = kp_out, vp_out  # aliased with kp_hbm/vp_hbm
    else:
        (o_ref, k_buf, v_buf, ksem, vsem,
         qg_ref, m_ref, l_ref, acc_ref) = refs[i0:]
        kp_src, vp_src = kp_hbm, vp_hbm
    KB = k_buf.shape[1]
    page_size = KB // N
    max_pages = pt_ref.shape[1]
    n_cells = seq_ref.shape[0]
    TQ, NH, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    KH = kv_heads
    G = NH // KH
    lyr = layer_ref[0]

    c = pl.program_id(0)
    total = total_ref[0]
    live = c < total
    b = seq_ref[c]
    qb = qb_ref[c]
    p = blk_ref[c]
    r = b * n_qb + qb

    def _pid_of(g):
        """Pool page id for global page stream index g (clamped for dead
        cells) — the quantized fold uses it to look up each page's scale."""
        cc = jnp.minimum(g // N, n_cells - 1)
        bb = seq_ref[cc]
        rr = bb * n_qb + qb_ref[cc]
        pi = blk_ref[cc] * N + g % N
        return pt_ref[bb, jnp.minimum(lopg_ref[rr] + pi, max_pages - 1)]

    def _copies(g):
        """DMA descriptors + go/no-go predicate for global page stream index
        g = cell*N + i. A page is fetched iff its cell is live and the page
        lies inside the cell's (b, qb) live range — the SAME predicate gates
        start and wait, so semaphore counts always pair. Page i of cell cc
        lands at offset i*page within ring slot cc % RB: the cell's N pages
        are CONTIGUOUS in VMEM and fold as one wide matmul."""
        cc = jnp.minimum(g // N, n_cells - 1)
        bb = seq_ref[cc]
        rr = bb * n_qb + qb_ref[cc]
        pi = blk_ref[cc] * N + g % N
        ok = (g < total * N) & (pi < livepg_ref[rr])
        pid = _pid_of(g)
        slot = cc % RB
        off = (g % N) * page_size
        s = g % (RB * N)
        kcp = pltpu.make_async_copy(
            kp_src.at[lyr, pid], k_buf.at[slot, pl.ds(off, page_size)],
            ksem.at[s],
        )
        vcp = pltpu.make_async_copy(
            vp_src.at[lyr, pid], v_buf.at[slot, pl.ds(off, page_size)],
            vsem.at[s],
        )
        return ok, kcp, vcp

    def _start(g):
        ok, kcp, vcp = _copies(g)

        @pl.when(ok)
        def _():
            kcp.start()
            vcp.start()

    @pl.when(live & (p == 0))
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        # per-GQA-group query scratch (see v1 notes: group-major scratch lets
        # the fold index groups dynamically from a fori_loop, and Mosaic
        # rejects the minor-dim collapse a row-packed layout would need)
        q4 = (
            q_ref[0] * jnp.asarray(sm_scale, q_ref.dtype)
        ).reshape(TQ, KH, G, D)
        for g in range(G):
            qg_ref[g] = q4[:, :, g].transpose(1, 0, 2)  # [KH, TQ, D]

    @pl.when(c == 0)
    def _():
        # warm-up: fill the ring's first RB-1 block slots; steady state below
        # tops off block c+RB-1 while consuming block c, so up to (RB-1)*N
        # page DMAs stay in flight across cell boundaries
        for g in range((RB - 1) * N):
            _start(jnp.int32(g))

    paged_end = lens_ref[b] - cl_ref[b]
    pos_q = pos_ref[0]  # [TQ]
    win = win_ref[0]

    def fold(k, v, kv_pos, valid):
        """One online-softmax update; k/v [KH, S, D], kv_pos/valid [S].

        Groups run under a fori_loop, NOT a Python loop: every unrolled fold
        would get its own scoped-vmem stack for the [KH, TQ, S] f32 score
        temporaries, while a loop body compiles once and reuses one stack
        (v1's measured 26 MB-vs-16 MB lesson). Inputs stay in their own
        dtype (bf16 in production: MXU-native)."""
        vis = (
            valid[None, None, :]
            & (kv_pos[None, None, :] <= pos_q[None, :, None])
            & (pos_q[None, :, None] >= 0)
            & (kv_pos[None, None, :] > pos_q[None, :, None] - win)
        )  # [1, TQ, S]

        def gbody(g, carry):
            s = lax.dot_general(
                qg_ref[g], k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [KH, TQ, S]
            if logit_softcap is not None:
                s = logit_softcap * jnp.tanh(s / logit_softcap)
            s = jnp.where(vis, s, NEG_INF)
            m_prev, l_prev = m_ref[g], l_ref[g]
            m_new = jnp.maximum(m_prev, s.max(axis=-1))
            alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            pij = jnp.exp(s - m_new[..., None])
            pij = jnp.where(vis, pij, 0.0)
            m_ref[g] = m_new
            l_ref[g] = l_prev * alpha + pij.sum(axis=-1)
            pv = lax.dot_general(
                pij.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [KH, TQ, D]
            acc_ref[g] = acc_ref[g] * alpha[..., None] + pv
            return carry

        lax.fori_loop(0, G, gbody, 0)

    # ---- paged KV: top off the ring, then ONE wide fold over the cell ----
    @pl.when(live)
    def _():
        for i in range(N):
            _start(c * N + i + (RB - 1) * N)
        for i in range(N):
            ok_i, kcp, vcp = _copies(c * N + i)

            @pl.when(ok_i)
            def _():
                kcp.wait()
                vcp.wait()

        @pl.when(p * N < livepg_ref[r])
        def _():
            slot = c % RB
            kb = k_buf[slot]
            vb = v_buf[slot]
            if quantized:
                # dequant at the ring exit: one [N, KH] scale block gathered
                # from the resident slab, broadcast over each page's slots
                sk = jnp.stack([ks_ref[0, _pid_of(c * N + i)] for i in range(N)])
                sv = jnp.stack([vs_ref[0, _pid_of(c * N + i)] for i in range(N)])
                kb = (
                    kb.astype(jnp.float32).reshape(N, page_size, KH, D)
                    * sk[:, None, :, None]
                ).reshape(KB, KH, D)
                vb = (
                    vb.astype(jnp.float32).reshape(N, page_size, KH, D)
                    * sv[:, None, :, None]
                ).reshape(KB, KH, D)
            k = kb.transpose(1, 0, 2)  # [KH, KB, D]
            v = vb.transpose(1, 0, 2)
            start = (lopg_ref[r] + p * N) * page_size
            idx = start + lax.iota(jnp.int32, KB)
            # slots of pages beyond the live range hold stale ring bytes;
            # the validity bound (idx >= paged_end there) masks their
            # scores, but v must ALSO be sanitized: 0 * garbage in the
            # pij @ v matmul is NaN when the never-fetched slot is NaN
            valid = idx < paged_end
            v = jnp.where(valid[None, :, None], v, 0.0)
            fold(k, v, idx, valid)

    # ---- fused paged-KV write: once per row, at its first cell ----------
    if fused_write and quantized:
        ps = page_size

        @pl.when(live & (qb == 0) & (p == 0) & (cl_ref[b] > 0))
        def _():
            s0 = paged_end              # chunk start (contiguous contract)
            e0 = s0 + cl_ref[b]
            lp0 = s0 // ps
            # quantize-in-kernel (ops/quant.py contract): FRESH pages
            # (page_start >= s0 — slot 0 is this chunk's) get scale =
            # amax/127 and fully-defined content (zeros beyond the chunk
            # end); the rare non-aligned HEAD page (page_start < s0, holds
            # this row's earlier tokens) keeps its OLD scale and clips new
            # tokens into it — rescaling it here would rewrite bytes the
            # SAME invocation's ring reads race against (scheduler chunks
            # are page-aligned in practice: prefill_chunk % page_size == 0,
            # so this path only runs for odd configs). New scales land in
            # the o_ksc/o_vsc output blocks; the wrapper scatters them into
            # the scales pool (a few KB — the page BYTES still cross HBM
            # exactly once, in int8).
            for j in range(max_write_pages):
                page_start = (lp0 + j) * ps
                pid = pt_ref[b, jnp.minimum(lp0 + j, max_pages - 1)]
                any_w = (page_start < e0) & (page_start + ps > s0)
                fresh = page_start >= s0
                src = page_start - s0 + fp_pad

                @pl.when(any_w)
                def _(j=j, page_start=page_start, pid=pid, src=src,
                      fresh=fresh):
                    gidx = page_start + lax.broadcasted_iota(
                        jnp.int32, (ps, 1, 1), 0
                    )
                    keep = (gidx >= s0) & (gidx < e0)
                    xk = jnp.where(
                        keep, kc_ref[0, pl.ds(src, ps)].astype(jnp.float32), 0.0
                    )
                    xv = jnp.where(
                        keep, vc_ref[0, pl.ds(src, ps)].astype(jnp.float32), 0.0
                    )
                    want_k = jnp.maximum(
                        jnp.max(jnp.abs(xk), axis=(0, 2)) / 127.0, 1e-8
                    )
                    want_v = jnp.maximum(
                        jnp.max(jnp.abs(xv), axis=(0, 2)) / 127.0, 1e-8
                    )
                    ns_k = jnp.where(fresh, want_k, ks_ref[0, pid])
                    ns_v = jnp.where(fresh, want_v, vs_ref[0, pid])
                    o_ksc[0, j] = ns_k
                    o_vsc[0, j] = ns_v
                    qk = jnp.clip(
                        jnp.round(xk / ns_k[None, :, None]), -127, 127
                    ).astype(wbuf_k.dtype)
                    qv = jnp.clip(
                        jnp.round(xv / ns_v[None, :, None]), -127, 127
                    ).astype(wbuf_v.dtype)

                    @pl.when(fresh)
                    def _():
                        wbuf_k[...] = qk
                        wbuf_v[...] = qv

                    @pl.when(~fresh)
                    def _():
                        # head page: read-modify-write; untouched slots keep
                        # their exact old bytes (old scale unchanged)
                        rk = pltpu.make_async_copy(
                            kp_out.at[lyr, pid], wbuf_k, rk_sem
                        )
                        rv = pltpu.make_async_copy(
                            vp_out.at[lyr, pid], wbuf_v, rv_sem
                        )
                        rk.start()
                        rv.start()
                        rk.wait()
                        rv.wait()
                        wbuf_k[...] = jnp.where(keep, qk, wbuf_k[...])
                        wbuf_v[...] = jnp.where(keep, qv, wbuf_v[...])

                    # single staging buffer: the write must land before the
                    # next page's quantization reuses it
                    wk = pltpu.make_async_copy(
                        wbuf_k, kp_out.at[lyr, pid], wk_sem.at[j]
                    )
                    wv = pltpu.make_async_copy(
                        wbuf_v, vp_out.at[lyr, pid], wv_sem.at[j]
                    )
                    wk.start()
                    wv.start()
                    wk.wait()
                    wv.wait()

    elif fused_write:
        ps = page_size

        @pl.when(live & (qb == 0) & (p == 0) & (cl_ref[b] > 0))
        def _():
            s0 = paged_end              # chunk start (contiguous contract)
            e0 = s0 + cl_ref[b]
            lp0 = s0 // ps

            def page_preds(j):
                page_start = (lp0 + j) * ps
                pid = pt_ref[b, jnp.minimum(lp0 + j, max_pages - 1)]
                any_w = (page_start < e0) & (page_start + ps > s0)
                full = (page_start >= s0) & (page_start + ps <= e0)
                src = page_start - s0 + fp_pad  # offset into padded chunk
                return page_start, pid, any_w, full, src

            # interior pages: one page-sized DMA straight from the chunk's
            # VMEM block; starts all go out first, waits batch below
            for j in range(max_write_pages):
                _, pid, any_w, full, src = page_preds(j)

                @pl.when(any_w & full)
                def _(j=j, pid=pid, src=src):
                    pltpu.make_async_copy(
                        kc_ref.at[0, pl.ds(src, ps)], kp_out.at[lyr, pid],
                        wk_sem.at[j],
                    ).start()
                    pltpu.make_async_copy(
                        vc_ref.at[0, pl.ds(src, ps)], vp_out.at[lyr, pid],
                        wv_sem.at[j],
                    ).start()

            # partial head/tail pages (at most one of each): read-modify-
            # write so slots outside [s0, e0) keep their exact old bytes —
            # bit-identical to the scatter path's dropped writes
            for j in range(max_write_pages):
                page_start, pid, any_w, full, src = page_preds(j)

                @pl.when(any_w & ~full)
                def _(j=j, page_start=page_start, pid=pid, src=src):
                    rk = pltpu.make_async_copy(
                        kp_out.at[lyr, pid], wbuf_k, rk_sem
                    )
                    rv = pltpu.make_async_copy(
                        vp_out.at[lyr, pid], wbuf_v, rv_sem
                    )
                    rk.start()
                    rv.start()
                    rk.wait()
                    rv.wait()
                    gidx = page_start + lax.broadcasted_iota(
                        jnp.int32, (ps, 1, 1), 0
                    )
                    keep = (gidx >= s0) & (gidx < e0)
                    wbuf_k[...] = jnp.where(
                        keep, kc_ref[0, pl.ds(src, ps)], wbuf_k[...]
                    )
                    wbuf_v[...] = jnp.where(
                        keep, vc_ref[0, pl.ds(src, ps)], wbuf_v[...]
                    )
                    wk = pltpu.make_async_copy(
                        wbuf_k, kp_out.at[lyr, pid], wk_sem.at[j]
                    )
                    wv = pltpu.make_async_copy(
                        wbuf_v, vp_out.at[lyr, pid], wv_sem.at[j]
                    )
                    wk.start()
                    wv.start()
                    wk.wait()
                    wv.wait()

            for j in range(max_write_pages):
                _, pid, any_w, full, src = page_preds(j)

                @pl.when(any_w & full)
                def _(j=j, pid=pid, src=src):
                    pltpu.make_async_copy(
                        kc_ref.at[0, pl.ds(src, ps)], kp_out.at[lyr, pid],
                        wk_sem.at[j],
                    ).wait()
                    pltpu.make_async_copy(
                        vc_ref.at[0, pl.ds(src, ps)], vp_out.at[lyr, pid],
                        wv_sem.at[j],
                    ).wait()

    # ---- last cell of (b, qb): fold the chunk, write the output ---------
    @pl.when(live & (p == cnt_ref[r] - 1))
    def _():
        CB = _FOLD_BLOCK

        @pl.when(qb * TQ < cl_ref[b])
        def _():
            # causal block-skip: entries past the block's last query are
            # invisible (positions are contiguous), so the loop bound is
            # min(cl, (qb+1)*TQ) — fully-masked upper-triangle sub-blocks
            # never execute
            bound = jnp.minimum(cl_ref[b], (qb + 1) * TQ)
            n_sub = pl.cdiv(bound, CB)

            def cbody(ci, carry):
                c0 = fp_pad + ci * CB
                kc = kc_ref[0, pl.ds(c0, CB)].transpose(1, 0, 2)
                vc = vc_ref[0, pl.ds(c0, CB)].transpose(1, 0, 2)
                cpos = cpos_ref[0, pl.ds(c0, CB)]  # -1 pad = invisible
                fold(kc, vc, cpos, cpos >= 0)
                return carry

            lax.fori_loop(0, n_sub, cbody, 0)

        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)[..., None]
        # [G, KH, TQ, D] -> [TQ, NH, D] with h = kh*G + g
        out = out.transpose(2, 1, 0, 3).reshape(TQ, NH, D)
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "sm_scale", "logit_softcap", "interpret", "pages_per_block",
        "prefetch_pages", "q_block", "fused_write",
    ),
)
def ragged_paged_attention_prefill(
    q: jnp.ndarray,          # [B, T, NH, D] chunk queries
    k_pages: jnp.ndarray,    # [P, page, KH, D] or [L, P, page, KH, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray, # [B, max_pages] int32
    positions: jnp.ndarray,  # [B, T] int32 absolute query positions, -1 pad
    kv_lens: jnp.ndarray,    # [B] int32 chunk-end lengths
    k_cur: jnp.ndarray,      # [B, T, KH, D] the chunk's K/V (post-write mode)
    v_cur: jnp.ndarray,
    cur_lens: jnp.ndarray,   # [B] valid chunk entries
    window=None,
    *,
    sm_scale: float | None = None,
    logit_softcap: float | None = None,
    interpret: bool = False,
    pages_per_block: int | None = None,
    prefetch_pages: int | None = None,
    q_block: int = 128,
    layer: jnp.ndarray | int | None = None,
    fused_write: bool = False,
    k_scales: jnp.ndarray | None = None,  # [P, KH] or [L, P, KH] f32 (int8)
    v_scales: jnp.ndarray | None = None,
):
    """Chunked-prefill attention over paged KV + in-register chunk K/V (v2).

    With ``k_scales/v_scales`` (int8 pools, ops/quant.py contract) the ring
    pages dequantize right before each cell's wide fold — half the HBM
    bytes per chunk — and ``fused_write=True`` quantizes the chunk's K/V
    in-kernel (fresh pages get amax/127 scales; a non-page-aligned head
    page clips into its existing scale), returning
    ``(out, k_pages, v_pages, k_scales, v_scales)``. ``k_cur/v_cur`` must
    arrive fp (they are the quantizer's input).

    Write-after-attend contract (ops/attention.stale_kv_positions): pool
    slots at positions >= kv_lens - cur_lens are stale — the chunk's K/V
    arrive in ``k_cur/v_cur`` and fold in at each query block's last cell.
    Valid chunk entries must be CONTIGUOUS and position-sorted: entry j
    holds position ``kv_lens - cur_lens + j`` for j < cur_lens (how the
    scheduler builds every chunk). Returns [B, T, NH, D] in q.dtype —
    matches the XLA oracle in interpret mode (tests assert equivalence at
    2e-5 in f32; the fold order differs, so output agreement is numerical
    — only the fused-write POOL contents are bit-identical, vs the
    scatter path).

    ``pages_per_block``: KV pages landed contiguously per packed grid cell
    (auto: ~512 KV slots), folded as ONE wide matmul — this is what fixes
    the v1 page-granular MXU fragmentation.

    ``prefetch_pages``: page DMAs kept in flight ahead of the cell being
    consumed (auto: ~2 cells' worth within a ~4 MB VMEM budget per pool
    array). Ring depth in cells is ``1 + ceil(prefetch/pages_per_block)``.

    ``fused_write=True``: additionally scatters the chunk's K/V into its
    pool pages from inside the kernel (pools aliased input->output) and
    returns ``(out, k_pages, v_pages)`` — replacing the post-scan
    ``write_kv_pages_all_layers`` pass on the prefill path. Untouched pool
    slots (before the chunk start, after the chunk end, other rows' pages)
    keep their exact old bytes.

    The grid is RAGGED: live (sequence, query-block, kv-block) cells pack
    to the front of a 1D grid sized for the bucket's worst case; trailing
    dead cells alias the last live cell (no DMA, no compute). Sliding
    windows shrink each query block's live page RANGE, not just the mask,
    so a 4k-window chunk at 128k context streams ~window bytes.
    """
    B, T, NH, D = q.shape
    quantized = k_scales is not None
    squeeze = k_pages.ndim == 4
    if squeeze:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        if quantized and k_scales.ndim == 2:
            k_scales = k_scales[None]
            v_scales = v_scales[None]
        layer = 0
    L, P, page_size, KH, _ = k_pages.shape
    max_pages = page_table.shape[1]
    G = NH // KH
    scale = sm_scale if sm_scale is not None else D**-0.5
    if pages_per_block is None:
        # ~512 contiguous KV slots per cell: wide enough to keep the MXU's
        # 128-lane S dim busy, small enough that the f32 score temporaries
        # ([KH, TQ, KB]) stay a few MB. int8 pools double the target —
        # half the ring bytes per slot buys a wider fold for the same VMEM
        # (the f32 score temporaries grow, hence x2 not x4; re-sweep with
        # scripts/profile_prefill.py --impl pallas_int8 when retuning)
        target = 1024 if quantized else 512
        pages_per_block = max(1, min(target // page_size, max_pages))
    N = max(1, min(pages_per_block, max_pages))
    KB = N * page_size
    n_blocks = -(-max_pages // N)
    if prefetch_pages is None:
        prefetch_pages = 2 * N  # two cells ahead
    block_bytes = KB * KH * D * jnp.dtype(k_pages.dtype).itemsize
    RB = max(2, 1 + -(-int(prefetch_pages) // N))
    RB = min(RB, max(2, (4 << 20) // max(block_bytes, 1)))
    TQ = min(q_block, T)
    n_qb = -(-T // TQ)
    if n_qb * TQ != T:
        pad = n_qb * TQ - T
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        positions = jnp.pad(positions, ((0, 0), (0, pad)), constant_values=-1)
    # chunk buffer layout: [fp_pad front | T entries | tail pad]. The front
    # pad (one page) makes every fused-write source slice a non-negative
    # fixed-size offset even for an unaligned head page; the tail pad covers
    # the last page's overhang and rounds to whole fold sub-blocks.
    CB = _FOLD_BLOCK
    FP = page_size
    # tail must cover both the fold's whole-CB sub-block slices (from FP)
    # and the fused write's last-page overhang (T + page_size from FP)
    Cw = FP + -(-(T + page_size) // CB) * CB
    # the chunk buffer stays fp under int8 pools: it is both the fold's
    # in-register operand and the fused quantizer's input
    chunk_dt = q.dtype if quantized else k_pages.dtype
    kc = jnp.zeros((B, Cw, KH, D), chunk_dt)
    vc = jnp.zeros((B, Cw, KH, D), chunk_dt)
    kc = lax.dynamic_update_slice(
        kc, k_cur.astype(chunk_dt), (0, FP, 0, 0)
    )
    vc = lax.dynamic_update_slice(
        vc, v_cur.astype(chunk_dt), (0, FP, 0, 0)
    )
    cl = jnp.asarray(cur_lens, jnp.int32)
    cpos = jnp.full((B, Cw), -1, jnp.int32)
    Tc = k_cur.shape[1]
    cpos = lax.dynamic_update_slice(
        cpos,
        jnp.where(
            (lax.broadcasted_iota(jnp.int32, (B, Tc), 1) < cl[:, None])
            & (positions[:, :Tc] >= 0),
            positions[:, :Tc],
            -1,
        ),
        (0, FP),
    )
    win = (
        jnp.full((1,), 2**30, jnp.int32)
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)
    MAXW = -(-T // page_size) + 1  # pool pages one chunk can touch

    # ---- ragged cell maps: pack live (b, qb, kv-block) cells ------------
    lens32 = kv_lens.astype(jnp.int32)
    pe = lens32 - cl                                   # [B] paged_end
    qbi = jnp.arange(n_qb, dtype=jnp.int32)
    # earliest valid query of block qb sits at position pe + qb*TQ; its
    # window opens the live page range at (that - win + 1) — later query
    # blocks of a windowed model skip early pages entirely
    qstart = pe[:, None] + qbi[None, :] * TQ           # [B, n_qb]
    lo = jnp.clip(qstart - win[0] + 1, 0, None)
    lo_pg = lo // page_size                            # [B, n_qb]
    hi_pg = -(-jnp.maximum(pe, 0) // page_size)        # [B]
    live_pg = jnp.maximum(hi_pg[:, None] - lo_pg, 0)   # [B, n_qb]
    qlive = (qbi[None, :] * TQ) < cl[:, None]
    live_pg = jnp.where(qlive, live_pg, 0)
    # every (b, qb) keeps >= 1 cell so padded rows / dead query blocks
    # still initialize and write their (zero) output block
    cells = jnp.clip(-(-live_pg // N), 1, n_blocks).astype(jnp.int32)
    rflat = cells.reshape(-1)                          # [B*n_qb]
    Rn = B * n_qb
    cs = jnp.cumsum(rflat).astype(jnp.int32)
    starts = cs - rflat
    n_cells = Rn * n_blocks
    cidx = jnp.arange(n_cells, dtype=jnp.int32)
    total = cs[Rn - 1]
    rrow = jnp.minimum(
        jnp.searchsorted(cs, cidx, side="right").astype(jnp.int32), Rn - 1
    )
    dead = cidx >= total
    # dead cells alias the LAST live cell: index maps repeat, so the
    # pipeline neither fetches nor writes for them
    seq_of = jnp.where(dead, B - 1, rrow // n_qb)
    qb_of = jnp.where(dead, n_qb - 1, rrow % n_qb)
    blk_of = jnp.where(dead, rflat[Rn - 1] - 1, cidx - starts[rrow])
    total_arr = cs[Rn - 1:Rn]

    NS = 12  # scalar-prefetch operand count

    def qrow(c, *refs):
        so, qo = refs[5], refs[6]
        return (so[c], qo[c], 0, 0)

    def prow(c, *refs):
        so, qo = refs[5], refs[6]
        return (so[c], qo[c])

    def crow(c, *refs):
        return (refs[5][c], 0, 0, 0)

    def crow2(c, *refs):
        return (refs[5][c], 0)

    def scrow(c, *refs):
        # scale slabs: the CURRENT layer's whole [P, KH] slice — constant
        # block index, so the pipeline fetches it once
        return (refs[4][0], 0, 0)

    def oscrow(c, *refs):
        return (refs[5][c], 0, 0)

    in_specs = [
        pl.BlockSpec((1, TQ, NH, D), qrow),
        pl.BlockSpec((1, TQ), prow),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec((1, Cw, KH, D), crow),
        pl.BlockSpec((1, Cw, KH, D), crow),
        pl.BlockSpec((1, Cw), crow2),
    ]
    operands = [q, positions, k_pages, v_pages, kc, vc, cpos]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, P, KH), scrow),
            pl.BlockSpec((1, P, KH), scrow),
        ]
        operands += [k_scales, v_scales]
    out_shapes = [jax.ShapeDtypeStruct((B, n_qb * TQ, NH, D), q.dtype)]
    out_specs = [pl.BlockSpec((1, TQ, NH, D), qrow)]
    scratch = [
        pltpu.VMEM((RB, KB, KH, D), k_pages.dtype),
        pltpu.VMEM((RB, KB, KH, D), v_pages.dtype),
        pltpu.SemaphoreType.DMA((RB * N,)),
        pltpu.SemaphoreType.DMA((RB * N,)),
    ]
    io_aliases = {}
    if fused_write:
        out_shapes += [
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ]
        out_specs += [
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ]
        # operand index counts scalar prefetch: pools sit at NS+2 / NS+3
        io_aliases = {NS + 2: 1, NS + 3: 2}
        if quantized:
            # per-row new scales for the (<= MAXW) written pages; the
            # wrapper scatters them into the scales pool after the call
            out_shapes += [
                jax.ShapeDtypeStruct((B, MAXW, KH), jnp.float32),
                jax.ShapeDtypeStruct((B, MAXW, KH), jnp.float32),
            ]
            out_specs += [
                pl.BlockSpec((1, MAXW, KH), oscrow),
                pl.BlockSpec((1, MAXW, KH), oscrow),
            ]
        scratch += [
            pltpu.SemaphoreType.DMA((MAXW,)),
            pltpu.SemaphoreType.DMA((MAXW,)),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((page_size, KH, D), k_pages.dtype),
            pltpu.VMEM((page_size, KH, D), v_pages.dtype),
        ]
    scratch += [
        pltpu.VMEM((G, KH, TQ, D), q.dtype),     # per-group queries
        pltpu.VMEM((G, KH, TQ), jnp.float32),
        pltpu.VMEM((G, KH, TQ), jnp.float32),
        pltpu.VMEM((G, KH, TQ, D), jnp.float32),
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=NS,
        grid=(n_cells,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _prefill_kernel, sm_scale=scale, kv_heads=KH,
        logit_softcap=logit_softcap, pages_per_block=N, ring_blocks=RB,
        n_qb=n_qb, fused_write=fused_write, fp_pad=FP,
        max_write_pages=MAXW, quantized=quantized,
    )
    if fused_write and quantized:
        # scale-scatter targets for the written pages, computed BEFORE the
        # aliased pallas_call (its operands are dead afterwards). The
        # validity mask mirrors the kernel's write predicate (any_w); a
        # non-fresh head page kept its old scale, so rewriting it is a
        # no-op, but masking dead rows keeps the scatter honest when the
        # o_* output blocks hold stale VMEM garbage (cl == 0 rows).
        s0_w = pe
        e0_w = lens32
        lp0_w = jnp.maximum(s0_w, 0) // page_size
        jw = jnp.arange(MAXW, dtype=jnp.int32)[None, :]
        logical_w = lp0_w[:, None] + jw
        pstart_w = logical_w * page_size
        any_w = (
            (pstart_w < e0_w[:, None])
            & (pstart_w + page_size > s0_w[:, None])
            & (cl[:, None] > 0)
            & (logical_w < max_pages)
        )
        pid_w = jnp.take_along_axis(
            page_table.astype(jnp.int32),
            jnp.clip(logical_w, 0, max_pages - 1), axis=1,
        )
        sc_target = jnp.where(any_w, pid_w, P).reshape(-1)  # P = dropped
        sc_layer = lyr[0]
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=tuple(out_shapes),
        interpret=interpret,
        input_output_aliases=io_aliases,
        # the default 16 MB scoped-vmem budget is a fraction of v5e's
        # physical VMEM; the f32 score temporaries of a TQ x KB cell need
        # more headroom than decode-sized cells
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(vmem_limit_bytes=100 * 1024 * 1024),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * T * NH * D * (max_pages * page_size + T),
            bytes_accessed=(
                2 * max_pages * page_size * KH * D * 2 * B
                + 2 * B * T * (NH + 2 * KH) * D
            ),
            transcendentals=B * NH * T * (max_pages * page_size + T),
        ),
    )(
        page_table.astype(jnp.int32), lens32, cl, win, lyr,
        seq_of, qb_of, blk_of, cells.reshape(-1), lo_pg.reshape(-1),
        live_pg.reshape(-1).astype(jnp.int32), total_arr,
        *operands,
    )
    if fused_write and quantized:
        out, kp_new, vp_new, o_ksc, o_vsc = outs
        # scatter the written pages' new scales into the scales pool: page
        # bytes crossed HBM once, in-kernel; the scales are a few KB
        ks_new = k_scales.at[sc_layer, sc_target].set(
            o_ksc.reshape(-1, KH), mode="drop"
        )
        vs_new = v_scales.at[sc_layer, sc_target].set(
            o_vsc.reshape(-1, KH), mode="drop"
        )
        if squeeze:
            kp_new, vp_new = kp_new[0], vp_new[0]
            ks_new, vs_new = ks_new[0], vs_new[0]
        return out[:, :T], kp_new, vp_new, ks_new, vs_new
    if fused_write:
        out, kp_new, vp_new = outs
        if squeeze:
            kp_new, vp_new = kp_new[0], vp_new[0]
        return out[:, :T], kp_new, vp_new
    return outs[0][:, :T]
