"""Pallas TPU kernel: ragged paged attention for the decode step.

Why a kernel (SURVEY.md §7 hard part #1): the XLA reference path
(ops/attention.py paged_attention_decode) gathers each sequence's pages into a
contiguous [B, S, KH, D] tensor in HBM *before* attending — that copy is pure
HBM-bandwidth waste in the bandwidth-bound decode regime. This kernel instead
streams each page HBM->VMEM exactly once, using the page table as a
scalar-prefetch argument so the block index map can chase page indirection,
and Pallas's grid pipeline double-buffers the page fetches behind the online-
softmax compute.

Layout: grid = (B, max_pages); for each sequence the page axis is innermost,
so the (m, l, acc) VMEM scratch persists across that sequence's pages (same
output block revisited) — the classic flash-decode accumulation. Query/kv
heads stay packed [KH, G, D] so all heads of a page are one batched MXU call.

Sliding-window attention (Mistral, Gemma-2's even layers) is handled by
remapping the page axis: the index map starts fetching at the first page
containing a visible KV slot (``(kv_len - window) // page_size``), so a
4096-window sequence at 128k context streams ~window bytes, not ~context
bytes. The window arrives as a scalar-prefetch operand, so per-layer window
sizes (Gemma-2 interleaves local/global) ride the decoder's layer scan.
Logit softcapping (Gemma-2) is a static transform on the scores.

Equivalent role in the reference: vLLM's CUDA PagedAttention decode kernel
(executed inside the engine image; configured by
helm/templates/deployment-vllm-multi.yaml in /root/reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    pt_ref,      # [B, max_pages] int32 page table
    lens_ref,    # [B] int32 kv lengths
    win_ref,     # [1] int32 window size (huge = full causal)
    cl_ref,      # [B] int32 valid current-window entries (has_cur mode)
    layer_ref,   # [1] int32 layer index into the stacked pools
    # blocks
    q_ref,       # [1, NH, D]
    *refs,       # N x (k_ref, v_ref) [1, 1, page_size, KH, D] each,
                 # [k_cur_ref, v_cur_ref ([1, C, KH, D]),] o_ref, m/l/acc
    sm_scale: float,
    kv_heads: int,
    logit_softcap: float | None,
    has_cur: bool,
    pages_per_block: int,
):
    N = pages_per_block
    kv_refs = refs[: 2 * N]  # k0, v0, k1, v1, ...
    rest = refs[2 * N:]
    if has_cur:
        # write-after-attend mode: the last cl_ref[b] tokens' pool slots are
        # stale; their K/V arrive in-register (a fused burst accumulates up
        # to C of them) and fold in on the last grid step
        k_cur_ref, v_cur_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    page_size = kv_refs[0].shape[2]
    NH, D = q_ref.shape[1], q_ref.shape[2]
    KH = kv_heads
    G = NH // KH

    @pl.when(p == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    kv_len = lens_ref[b]
    # paged slots hold positions < paged_end; in has_cur mode the final
    # cl_ref[b] slots (the in-register window) are stale in the pool
    paged_end = kv_len - cl_ref[b] if has_cur else kv_len
    lo = jnp.maximum(kv_len - win_ref[0], 0)   # first visible KV slot

    # N pages per grid cell (unrolled): each page is its own input block with
    # the single-page layout — same compute per page as the N=1 kernel, but
    # the grid (and its per-cell pipeline overhead, the reason small pages
    # used to decode slower) shrinks N-fold. No cross-page reshapes or lane
    # slicing, which Mosaic rejects for these layouts.
    for i in range(N):
        # this sub-block's first slot
        start = (lo // page_size + p * N + i) * page_size

        @pl.when(start < paged_end)
        def _(k_ref=kv_refs[2 * i], v_ref=kv_refs[2 * i + 1], start=start):
            q = (q_ref[0].astype(jnp.float32) * sm_scale).reshape(KH, G, D)
            k = k_ref[0, 0].astype(jnp.float32).transpose(1, 0, 2)  # [KH, page, D]
            v = v_ref[0, 0].astype(jnp.float32).transpose(1, 0, 2)
            # batched over KH: [KH, G, D] x [KH, page, D] -> [KH, G, page]
            scores = lax.dot_general(
                q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
            )
            if logit_softcap is not None:
                scores = logit_softcap * jnp.tanh(scores / logit_softcap)
            idx = start + lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
            visible = (idx >= lo) & (idx < paged_end)
            scores = jnp.where(visible, scores, NEG_INF)

            m_prev, l_prev = m_ref[...], l_ref[...]
            m_new = jnp.maximum(m_prev, scores.max(axis=-1))
            alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            pij = jnp.exp(scores - m_new[..., None])
            pij = jnp.where(visible, pij, 0.0)
            m_ref[...] = m_new
            l_ref[...] = l_prev * alpha + pij.sum(axis=-1)
            # [KH, G, page] x [KH, page, D] -> [KH, G, D]
            pv = lax.dot_general(
                pij, v, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
            )
            acc_ref[...] = acc_ref[...] * alpha[..., None] + pv

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        m_prev, l_prev, acc = m_ref[...], l_ref[...], acc_ref[...]
        if has_cur:
            # one extra online-softmax update over the in-register window
            # (entries j < cl at positions paged_end + j; the final entry,
            # the current token, is always causally visible)
            q = (q_ref[0].astype(jnp.float32) * sm_scale).reshape(KH, G, D)
            kc = k_cur_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # [KH, C, D]
            vc = v_cur_ref[0].astype(jnp.float32).transpose(1, 0, 2)
            C = kc.shape[1]
            s_cur = lax.dot_general(
                q, kc, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [KH, G, C]
            if logit_softcap is not None:
                s_cur = logit_softcap * jnp.tanh(s_cur / logit_softcap)
            j = lax.broadcasted_iota(jnp.int32, (1, 1, C), 2)
            pos_j = paged_end + j
            vis = (j < cl_ref[b]) & (pos_j >= lo)
            s_cur = jnp.where(vis, s_cur, NEG_INF)
            m_new = jnp.maximum(m_prev, s_cur.max(axis=-1))
            alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            p_cur = jnp.exp(s_cur - m_new[..., None])
            p_cur = jnp.where(vis, p_cur, 0.0)
            l_prev = l_prev * alpha + p_cur.sum(axis=-1)
            pv = lax.dot_general(
                p_cur, vc, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            acc = acc * alpha[..., None] + pv
        out = acc / jnp.maximum(l_prev, 1e-30)[..., None]
        o_ref[0] = out.reshape(NH, D).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "logit_softcap", "interpret", "pages_per_block"),
)
def ragged_paged_attention_decode(
    q: jnp.ndarray,          # [B, NH, D]
    k_pages: jnp.ndarray,    # [P, page_size, KH, D] or [L, P, page, KH, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray, # [B, max_pages] int32
    seq_lens: jnp.ndarray,   # [B] int32
    window=None,             # scalar int (static or traced); None = full causal
    *,
    sm_scale: float | None = None,
    logit_softcap: float | None = None,
    interpret: bool = False,
    k_cur: jnp.ndarray | None = None,  # [B, KH, D] or [B, C, KH, D]
    v_cur: jnp.ndarray | None = None,
    cur_lens: jnp.ndarray | None = None,  # [B] valid window entries (1..C)
    pages_per_block: int | None = None,
    layer: jnp.ndarray | int | None = None,  # index into stacked pools
) -> jnp.ndarray:
    """Decode attention over paged KV, streaming pages HBM->VMEM.

    With ``k_cur/v_cur`` (write-after-attend mode), pool slots at positions
    >= ``seq_lens - cur_lens`` are treated as stale and the in-register
    window folds in instead: entry j holds the token at absolute position
    ``seq_lens - cur_lens + j`` (valid for j < cur_lens). A fused decode
    burst defers all its KV scatters this way — the pool stays read-only
    for the whole burst. [B, KH, D] k_cur means C=1 (single current token).
    Returns [B, NH, D] in q.dtype. Matches
    ops/attention.paged_attention_decode (the XLA oracle) — tests assert
    equivalence.

    Stacked pools + ``layer``: passing the whole [L, P, page, KH, D] pool
    and a (traced) layer index lets the per-layer scan stream pages straight
    out of the stacked array — a per-layer ``k_pages[l]`` at the call site
    would materialize a pool-sized dynamic-slice copy every layer (profiled
    at ~1.5 ms/step on v5e), because XLA cannot fuse a slice into a
    pallas_call operand.

    ``pages_per_block``: pages fetched per grid cell, each as its own input
    block (auto: ~128 KV slots per cell). The per-cell pipeline overhead is
    what made small pages slow (876 tok/s at page 16 vs 1,501 at 128 on
    v5e, engine/config.py) — grouping fetches recovers the throughput while
    keeping page_size (the prefix-cache sharing granule) fine.
    """
    B, NH, D = q.shape
    if k_pages.ndim == 4:  # single-layer pools: free leading-axis view
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        layer = 0
    _, _, page_size, KH, _ = k_pages.shape
    max_pages = page_table.shape[1]
    G = NH // KH
    scale = sm_scale if sm_scale is not None else D**-0.5
    has_cur = k_cur is not None
    if has_cur and k_cur.ndim == 3:
        k_cur = k_cur[:, None]  # [B, KH, D] -> C=1 window
        v_cur = v_cur[:, None]
    if pages_per_block is None:
        # ~128 KV slots per cell for the short-context buckets this was
        # tuned on; long-context buckets (>=128 pages, e.g. 9k-token QA
        # histories in a 256-page bucket) quadruple the cell count and the
        # per-cell pipeline overhead was measured dominating the step
        # (~40 ms/step at B=32 x 256 pages) — target ~512 slots there
        target = 512 if max_pages >= 128 else 128
        pages_per_block = max(1, min(target // page_size, max_pages))
    N = max(1, min(pages_per_block, max_pages))
    n_blocks = -(-max_pages // N)
    win = (
        jnp.full((1,), 2**30, jnp.int32)
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    cl = (
        jnp.ones((B,), jnp.int32)
        if cur_lens is None
        else jnp.asarray(cur_lens, jnp.int32)
    )
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)

    def kv_index(i):
        def index(b, p, pt, lens, w, _cl, l):
            # start fetching at the first page with a visible slot so
            # windowed layers stream ~window bytes regardless of context
            lo_page = jnp.maximum(lens[b] - w[0], 0) // page_size
            return (
                l[0],
                pt[b, jnp.minimum(lo_page + p * N + i, max_pages - 1)],
                0, 0, 0,
            )

        return index

    row = lambda b, p, pt, lens, w, _cl, l: (b, 0, 0)
    row4 = lambda b, p, pt, lens, w, _cl, l: (b, 0, 0, 0)
    in_specs = [pl.BlockSpec((1, NH, D), row)]
    operands = [q]
    for i in range(N):
        in_specs += [
            pl.BlockSpec((1, 1, page_size, KH, D), kv_index(i)),
            pl.BlockSpec((1, 1, page_size, KH, D), kv_index(i)),
        ]
        operands += [k_pages, v_pages]
    if has_cur:
        C = k_cur.shape[1]
        in_specs += [
            pl.BlockSpec((1, C, KH, D), row4),
            pl.BlockSpec((1, C, KH, D), row4),
        ]
        operands += [k_cur, v_cur]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, NH, D), row),
        scratch_shapes=[
            pltpu.VMEM((KH, G), jnp.float32),
            pltpu.VMEM((KH, G), jnp.float32),
            pltpu.VMEM((KH, G, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, sm_scale=scale, kv_heads=KH,
        logit_softcap=logit_softcap, has_cur=has_cur, pages_per_block=N,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, NH, D), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * B * NH * D * max_pages * page_size,
            bytes_accessed=(
                2 * max_pages * page_size * KH * D * 2 * B + B * NH * D * 4
            ),
            transcendentals=B * NH * max_pages * page_size,
        ),
    )(
        page_table.astype(jnp.int32), seq_lens.astype(jnp.int32), win, cl,
        lyr, *operands,
    )


def ragged_paged_attention_decode_sharded(
    mesh,
    q: jnp.ndarray,          # [B, NH, D], B sharded over dp / NH over tp
    k_pages: jnp.ndarray,    # [P, page_size, KH, D], KH sharded over tp
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray, # [B, max_pages]
    seq_lens: jnp.ndarray,   # [B]
    window=None,
    *,
    sm_scale: float | None = None,
    logit_softcap: float | None = None,
    interpret: bool = False,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    cur_lens: jnp.ndarray | None = None,
    layer: jnp.ndarray | int | None = None,
) -> jnp.ndarray:
    """The decode kernel on a multi-device mesh via manual shard_map.

    GSPMD cannot partition a pallas_call, so the north-star TP config (v5e-8,
    kv heads sharded over tp per shardings.KV_PAGES_SPEC) previously fell
    back to the XLA gather path whose HBM copy the kernel exists to avoid.
    Each (dp, tp) shard runs the kernel on its local batch rows and kv-head
    slice: attention is embarrassingly parallel over both axes (GQA groups
    stay whole because NH and KH divide by tp together), and page indices are
    global pool coordinates valid on every shard.

    sp/ep are ALSO mapped, with no spec mention: decode activations are
    replicated along them (sp shards the token dim of long prefills, ep the
    expert weights — neither shards a 1-token decode), so each (sp, ep)
    shard redundantly computes its (dp, tp) slice. Mapping them manually is
    what keeps GSPMD from trying — and failing — to partition the
    pallas_call along those axes, which is why sp/ep/pp serving configs
    used to regress decode to the XLA gather path (engine/runner.py).
    Under pp this function is called INSIDE the pipeline's shard_map over
    {pp} (parallel/pipeline.py serving_layer_pipeline) with stage-local
    layer pools — nested manual regions over disjoint axes.
    """
    from jax.sharding import PartitionSpec as P

    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else D**-0.5

    has_cur = k_cur is not None
    if has_cur and k_cur.ndim == 3:
        k_cur = k_cur[:, None]  # [B, KH, D] -> C=1 window
        v_cur = v_cur[:, None]
    if has_cur and cur_lens is None:
        cur_lens = jnp.ones(q.shape[:1], jnp.int32)
    if k_pages.ndim == 4:  # single-layer pools
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        layer = 0
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)

    def body(q, kp, vp, pt, lens, l, *cur):
        kc, vc, cl = cur if has_cur else (None, None, None)
        return ragged_paged_attention_decode(
            q, kp, vp, pt, lens, window,
            sm_scale=scale, logit_softcap=logit_softcap, interpret=interpret,
            k_cur=kc, v_cur=vc, cur_lens=cl, layer=l[0],
        )

    head = P("dp", "tp", None)
    pool = P(None, None, None, "tp", None)
    in_specs = [head, pool, pool, P("dp", None), P("dp"), P()]
    operands = [q, k_pages, v_pages, page_table, seq_lens, lyr]
    if has_cur:
        # the window's KH axis shards over tp like the pool's
        in_specs += [P("dp", None, "tp", None), P("dp", None, "tp", None), P("dp")]
        operands += [k_cur, v_cur, cur_lens]
    # only axes the mesh actually has, and never an axis some caller already
    # made manual (the pp pipeline region). When called inside a manual
    # region the context mesh (with those axes marked Manual) must be the
    # one passed to the nested shard_map, not the concrete mesh.
    from jax.sharding import get_abstract_mesh

    ctx = get_abstract_mesh()
    manual_already = (
        set(ctx.manual_axes) if ctx is not None and not ctx.empty else set()
    )
    sm_mesh = mesh if not manual_already else ctx
    manual = ({"dp", "tp", "sp", "ep"} & set(mesh.axis_names)) - manual_already
    out = jax.shard_map(
        body,
        mesh=sm_mesh,
        axis_names=manual,
        in_specs=tuple(in_specs),
        out_specs=head,
        check_vma=False,
    )(*operands)
    return out
