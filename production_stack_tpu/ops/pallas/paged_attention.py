"""Pallas TPU kernel: ragged paged attention for the decode step (v2).

Why a kernel (SURVEY.md §7 hard part #1): the XLA reference path
(ops/attention.py paged_attention_decode) gathers each sequence's pages into a
contiguous [B, S, KH, D] tensor in HBM *before* attending — that copy is pure
HBM-bandwidth waste in the bandwidth-bound decode regime. This kernel streams
each page HBM->VMEM exactly once instead.

v2 restructures the memory pipeline around two ideas (docs/benchmarking.md
"Hardware ceilings": page-scattered reads measured 14-30 GB/s vs ~200 GB/s
contiguous — the decode-step floor for long-context QA):

1. **Ragged packed grid.** v1 ran grid = (B, max_pages_bucket): a 50-page
   sequence in a 256-page bucket still executed ~200 dead grid cells whose
   index map clamped to the last page (refetch + masked compute). v2 derives
   each sequence's LIVE block count from ``kv_lens`` (and the sliding
   window) on the host side, packs all live (sequence, block) cells into a
   1D grid, and pads with no-op cells whose index maps alias the last live
   cell (no DMA, no compute). Decode cost therefore scales with the batch's
   REAL total context, not with B x bucket — which is what makes
   mixed-length decode batches (the multi-round-QA shape) cheap.

2. **Deep page prefetch.** v1 fetched N pages per cell as N separate small
   BlockSpec inputs, so at most one cell's worth of page DMAs overlapped
   compute and per-cell pipeline overhead dominated at small pages (876
   tok/s at page 16 vs 1,501 at 128 on v5e). v2 leaves the pools in HBM
   (``memory_space=ANY``) and drives a manually multi-buffered VMEM ring of
   page copies with ``pltpu.make_async_copy``: R page DMAs stay in flight
   across cell boundaries (R = ``prefetch_pages``), so the HBM pipeline
   stays full regardless of page size or cell shape.

Layout within a cell is unchanged from v1: query/kv heads stay packed
[KH, G, D] so all heads of a page are one batched MXU call, and the
(m, l, acc) VMEM scratch persists across a sequence's consecutive cells —
the classic flash-decode accumulation.

Sliding-window attention (Mistral, Gemma-2's even layers) is handled by
starting each sequence's live range at the first page containing a visible
KV slot (``(kv_len - window) // page_size``), so a 4096-window sequence at
128k context streams ~window bytes, not ~context bytes. The window arrives
as a scalar-prefetch operand, so per-layer window sizes (Gemma-2
interleaves local/global) ride the decoder's layer scan. Logit softcapping
(Gemma-2) is a static transform on the scores.

Measure the achieved page-streaming HBM GB/s with
``scripts/profile_decode.py`` (per (batch, context, page_size) bucket, plus
a mixed-length case that checks cost scales with real ``kv_lens``).

Equivalent role in the reference: vLLM's CUDA PagedAttention decode kernel
(executed inside the engine image; configured by
helm/templates/deployment-vllm-multi.yaml in /root/reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    pt_ref,      # [B, max_pages] int32 page table
    lens_ref,    # [B] int32 kv lengths
    win_ref,     # [1] int32 window size (huge = full causal)
    cl_ref,      # [B] int32 valid current-window entries (has_cur mode)
    layer_ref,   # [1] int32 layer index into the stacked pools
    seq_ref,     # [C_CELLS] int32 packed cell -> batch row
    blk_ref,     # [C_CELLS] int32 packed cell -> block index within the row
    cells_ref,   # [B] int32 live cell count per row (>= 1)
    livepg_ref,  # [B] int32 live page count per row (the packing's source
                 # of truth — the kernel must never re-derive it)
    total_ref,   # [1] int32 total live cells
    # inputs
    q_ref,       # [1, NH, D] (current cell's row)
    kp_hbm,      # [L, P, page_size, KH, D], memory_space=ANY (stays in HBM)
    vp_hbm,
    *refs,       # [ks_ref, vs_ref ([1, P, KH] f32 scale slabs, quantized),]
                 # [k_cur_ref, v_cur_ref ([1, C, KH, D]),] o_ref,
                 # k_buf/v_buf ([R, page, KH, D] VMEM ring), ksem/vsem,
                 # m/l/acc scratch
    sm_scale: float,
    kv_heads: int,
    logit_softcap: float | None,
    has_cur: bool,
    pages_per_block: int,
    prefetch: int,
    quantized: bool = False,
):
    i0 = 0
    if quantized:
        # int8 pools: the current layer's [P, KH] scale slabs ride as
        # whole VMEM blocks (constant index map — fetched once), and each
        # page dequantizes right after its DMA lands in the ring. The fp
        # values never exist in HBM — only the halved int8 byte stream does.
        ks_ref, vs_ref = refs[0], refs[1]
        i0 = 2
    if has_cur:
        # write-after-attend mode: the last cl_ref[b] tokens' pool slots are
        # stale; their K/V arrive in-register (a fused burst accumulates up
        # to C of them) and fold in on the row's last live cell
        (k_cur_ref, v_cur_ref, o_ref, k_buf, v_buf, ksem, vsem,
         m_ref, l_ref, acc_ref) = refs[i0:]
    else:
        (o_ref, k_buf, v_buf, ksem, vsem,
         m_ref, l_ref, acc_ref) = refs[i0:]
    N = pages_per_block
    R = prefetch
    page_size = k_buf.shape[1]
    max_pages = pt_ref.shape[1]
    n_cells = seq_ref.shape[0]
    NH, D = q_ref.shape[1], q_ref.shape[2]
    KH = kv_heads
    G = NH // KH
    lyr = layer_ref[0]

    c = pl.program_id(0)
    total = total_ref[0]
    live = c < total
    b = seq_ref[c]
    p = blk_ref[c]

    def _copies(g):
        """DMA descriptors (and their go/no-go predicate) for global
        page-stream index g = cell*N + i. A page is fetched iff its cell is
        live and it lies inside its row's live page range (livepg_ref, the
        same array the host packed the grid from) — the SAME predicate
        gates start and wait, so semaphore counts always pair. Also returns
        the page id so the quantized path can look up its scale row."""
        cc = jnp.minimum(g // N, n_cells - 1)
        bb = seq_ref[cc]
        pi = blk_ref[cc] * N + g % N  # page offset within the live range
        lo_pg = jnp.maximum(lens_ref[bb] - win_ref[0], 0) // page_size
        ok = (g < total * N) & (pi < livepg_ref[bb])
        pid = pt_ref[bb, jnp.minimum(lo_pg + pi, max_pages - 1)]
        s = g % R
        kcp = pltpu.make_async_copy(kp_hbm.at[lyr, pid], k_buf.at[s], ksem.at[s])
        vcp = pltpu.make_async_copy(vp_hbm.at[lyr, pid], v_buf.at[s], vsem.at[s])
        return ok, pid, kcp, vcp

    def _start(g):
        ok, _, kcp, vcp = _copies(g)

        @pl.when(ok)
        def _():
            kcp.start()
            vcp.start()

    @pl.when(live & (p == 0))
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(c == 0)
    def _():
        # warm-up: fill the ring; steady state below tops it off with copy
        # g+R-1 as it consumes copy g, so R page DMAs stay in flight
        for g in range(R - 1):
            _start(jnp.int32(g))

    kv_len = lens_ref[b]
    # paged slots hold positions < paged_end; in has_cur mode the final
    # cl_ref[b] slots (the in-register window) are stale in the pool
    paged_end = kv_len - cl_ref[b] if has_cur else kv_len
    lo = jnp.maximum(kv_len - win_ref[0], 0)   # first visible KV slot
    lo_pg = lo // page_size

    for i in range(N):

        @pl.when(live)
        def _(i=i):
            g = c * N + i
            _start(g + R - 1)
            ok, pid, kcp, vcp = _copies(g)

            @pl.when(ok)
            def _():
                kcp.wait()
                vcp.wait()
                s = g % R
                q = (q_ref[0].astype(jnp.float32) * sm_scale).reshape(KH, G, D)
                k = k_buf[s].astype(jnp.float32).transpose(1, 0, 2)  # [KH, page, D]
                v = v_buf[s].astype(jnp.float32).transpose(1, 0, 2)
                if quantized:
                    # dequant at the VMEM ring exit: per-page per-kv-head
                    # scale rows looked up from the resident slab
                    k = k * ks_ref[0, pid][:, None, None]
                    v = v * vs_ref[0, pid][:, None, None]
                # batched over KH: [KH, G, D] x [KH, page, D] -> [KH, G, page]
                scores = lax.dot_general(
                    q, k, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                if logit_softcap is not None:
                    scores = logit_softcap * jnp.tanh(scores / logit_softcap)
                start = (lo_pg + p * N + i) * page_size
                idx = start + lax.broadcasted_iota(
                    jnp.int32, (1, 1, page_size), 2
                )
                visible = (idx >= lo) & (idx < paged_end)
                scores = jnp.where(visible, scores, NEG_INF)

                m_prev, l_prev = m_ref[...], l_ref[...]
                m_new = jnp.maximum(m_prev, scores.max(axis=-1))
                alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
                pij = jnp.exp(scores - m_new[..., None])
                pij = jnp.where(visible, pij, 0.0)
                m_ref[...] = m_new
                l_ref[...] = l_prev * alpha + pij.sum(axis=-1)
                # [KH, G, page] x [KH, page, D] -> [KH, G, D]
                pv = lax.dot_general(
                    pij, v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                acc_ref[...] = acc_ref[...] * alpha[..., None] + pv

    @pl.when(live & (p == cells_ref[b] - 1))
    def _():
        m_prev, l_prev, acc = m_ref[...], l_ref[...], acc_ref[...]
        if has_cur:
            # one extra online-softmax update over the in-register window
            # (entries j < cl at positions paged_end + j; the final entry,
            # the current token, is always causally visible)
            q = (q_ref[0].astype(jnp.float32) * sm_scale).reshape(KH, G, D)
            kc = k_cur_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # [KH, C, D]
            vc = v_cur_ref[0].astype(jnp.float32).transpose(1, 0, 2)
            C = kc.shape[1]
            s_cur = lax.dot_general(
                q, kc, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )  # [KH, G, C]
            if logit_softcap is not None:
                s_cur = logit_softcap * jnp.tanh(s_cur / logit_softcap)
            j = lax.broadcasted_iota(jnp.int32, (1, 1, C), 2)
            pos_j = paged_end + j
            vis = (j < cl_ref[b]) & (pos_j >= lo)
            s_cur = jnp.where(vis, s_cur, NEG_INF)
            m_new = jnp.maximum(m_prev, s_cur.max(axis=-1))
            alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            p_cur = jnp.exp(s_cur - m_new[..., None])
            p_cur = jnp.where(vis, p_cur, 0.0)
            l_prev = l_prev * alpha + p_cur.sum(axis=-1)
            pv = lax.dot_general(
                p_cur, vc, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            acc = acc * alpha[..., None] + pv
        out = acc / jnp.maximum(l_prev, 1e-30)[..., None]
        o_ref[0] = out.reshape(NH, D).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "sm_scale", "logit_softcap", "interpret", "pages_per_block",
        "prefetch_pages",
    ),
)
def ragged_paged_attention_decode(
    q: jnp.ndarray,          # [B, NH, D]
    k_pages: jnp.ndarray,    # [P, page_size, KH, D] or [L, P, page, KH, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray, # [B, max_pages] int32
    seq_lens: jnp.ndarray,   # [B] int32
    window=None,             # scalar int (static or traced); None = full causal
    *,
    sm_scale: float | None = None,
    logit_softcap: float | None = None,
    interpret: bool = False,
    k_cur: jnp.ndarray | None = None,  # [B, KH, D] or [B, C, KH, D]
    v_cur: jnp.ndarray | None = None,
    cur_lens: jnp.ndarray | None = None,  # [B] valid window entries (1..C)
    pages_per_block: int | None = None,
    prefetch_pages: int | None = None,
    layer: jnp.ndarray | int | None = None,  # index into stacked pools
    k_scales: jnp.ndarray | None = None,  # [P, KH] or [L, P, KH] f32 (int8 pools)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Decode attention over paged KV, streaming pages HBM->VMEM.

    With ``k_scales/v_scales`` (int8 pools, ops/quant.py contract) each
    page dequantizes right after its DMA lands in the VMEM ring — HBM
    streams HALF the bytes and fp values never round-trip through it. The
    current layer's [P, KH] scale slabs stay VMEM-resident (fetched once,
    constant index map; P*KH*4 bytes each — ~256 KB at 8k pages x 8 heads).
    ``k_cur/v_cur`` stay fp: the in-register window never quantizes.

    With ``k_cur/v_cur`` (write-after-attend mode), pool slots at positions
    >= ``seq_lens - cur_lens`` are treated as stale and the in-register
    window folds in instead: entry j holds the token at absolute position
    ``seq_lens - cur_lens + j`` (valid for j < cur_lens). A fused decode
    burst defers all its KV scatters this way — the pool stays read-only
    for the whole burst. [B, KH, D] k_cur means C=1 (single current token).
    Returns [B, NH, D] in q.dtype. Matches
    ops/attention.paged_attention_decode (the XLA oracle) — tests assert
    equivalence (atol 2e-5 in f32, 3e-2 in bf16).

    Stacked pools + ``layer``: passing the whole [L, P, page, KH, D] pool
    and a (traced) layer index lets the per-layer scan stream pages straight
    out of the stacked array — a per-layer ``k_pages[l]`` at the call site
    would materialize a pool-sized dynamic-slice copy every layer (profiled
    at ~1.5 ms/step on v5e), because XLA cannot fuse a slice into a
    pallas_call operand.

    ``pages_per_block``: pages processed per packed grid cell (auto: ~128 KV
    slots per cell, ~512 for >=128-page buckets). With the v2 DMA ring this
    mostly sets grid-bookkeeping granularity, not pipeline depth.

    ``prefetch_pages``: depth of the VMEM page-copy ring — how many page
    DMAs stay in flight ahead of compute (auto: up to 8, bounded by a ~2 MB
    per-array VMEM budget). This is what keeps the HBM pipeline full at
    small pages; v1's per-cell BlockSpec fetches were the measured
    876 -> 1,501 tok/s page-16-vs-128 cliff (engine/config.py).

    The grid itself is RAGGED: live (sequence, block) cells pack to the
    front of a 1D grid sized for the bucket's worst case, and trailing dead
    cells alias the last live cell's indices (no DMA, no compute) — so a
    50-page sequence in a 256-page bucket costs ~50 pages of work, and a
    mixed-length batch costs the sum of its REAL contexts.
    """
    B, NH, D = q.shape
    quantized = k_scales is not None
    if k_pages.ndim == 4:  # single-layer pools: free leading-axis view
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        if quantized and k_scales.ndim == 2:
            k_scales = k_scales[None]
            v_scales = v_scales[None]
        layer = 0
    _, P_pool, page_size, KH, _ = k_pages.shape
    max_pages = page_table.shape[1]
    G = NH // KH
    scale = sm_scale if sm_scale is not None else D**-0.5
    has_cur = k_cur is not None
    if has_cur and k_cur.ndim == 3:
        k_cur = k_cur[:, None]  # [B, KH, D] -> C=1 window
        v_cur = v_cur[:, None]
    if pages_per_block is None:
        # ~128 KV slots of bookkeeping per cell for short-context buckets;
        # long-context buckets (>=128 pages) use ~512 — with the DMA ring
        # the cell size no longer bounds fetch depth, it only amortizes the
        # per-cell grid/index-map overhead. int8 pools double the slot
        # target: each slot costs half the bytes, so the same VMEM/DMA
        # budget amortizes twice the bookkeeping (re-sweep with
        # scripts/profile_decode.py --impl pallas_int8 when retuning)
        target = 512 if max_pages >= 128 else 128
        if jnp.dtype(k_pages.dtype).itemsize == 1:
            target *= 2
        pages_per_block = max(1, min(target // page_size, max_pages))
    N = max(1, min(pages_per_block, max_pages))
    n_blocks = -(-max_pages // N)
    n_cells = B * n_blocks
    if prefetch_pages is None:
        # ring depth: up to 8 pages in flight, bounded by ~2 MB of VMEM per
        # pool array (k and v each get a ring this size)
        slot_bytes = page_size * KH * D * jnp.dtype(k_pages.dtype).itemsize
        prefetch_pages = max(2, min(8, (2 << 20) // max(slot_bytes, 1)))
    R = max(2, int(prefetch_pages))
    win = (
        jnp.full((1,), 2**30, jnp.int32)
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    cl = (
        jnp.ones((B,), jnp.int32)
        if cur_lens is None
        else jnp.asarray(cur_lens, jnp.int32)
    )
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)

    # ragged cell maps: pack each row's live blocks (pages holding visible,
    # non-stale KV slots) into a 1D grid; every row keeps >= 1 cell so
    # padded rows (kv_len 0) still initialize + write their (zero) output
    lens32 = seq_lens.astype(jnp.int32)
    pe = lens32 - cl if has_cur else lens32
    lo_pg = jnp.maximum(lens32 - win[0], 0) // page_size
    live_pg = jnp.maximum(-(-jnp.maximum(pe, 0) // page_size) - lo_pg, 0)
    cells = jnp.clip(-(-live_pg // N), 1, n_blocks).astype(jnp.int32)
    cs = jnp.cumsum(cells).astype(jnp.int32)       # [B] end cell per row
    starts = cs - cells                            # [B] first cell per row
    cidx = jnp.arange(n_cells, dtype=jnp.int32)
    total = cs[B - 1]
    row = jnp.minimum(
        jnp.searchsorted(cs, cidx, side="right").astype(jnp.int32), B - 1
    )
    dead = cidx >= total
    # dead cells alias the LAST live cell (row B-1's final block): index
    # maps repeat, so the pipeline neither fetches nor writes for them
    seq_of = jnp.where(dead, B - 1, row)
    blk_of = jnp.where(dead, cells[B - 1] - 1, cidx - starts[row])
    total_arr = cs[B - 1:]

    def row3(c, pt, lens, w, _cl, l, so, bo, ce, lp, tot):
        return (so[c], 0, 0)

    def row4(c, pt, lens, w, _cl, l, so, bo, ce, lp, tot):
        return (so[c], 0, 0, 0)

    def srow(c, pt, lens, w, _cl, l, so, bo, ce, lp, tot):
        # scale slabs: the whole [P, KH] slice of the CURRENT layer; the
        # constant block index means the pipeline fetches it once
        return (l[0], 0, 0)

    in_specs = [
        pl.BlockSpec((1, NH, D), row3),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, P_pool, KH), srow),
            pl.BlockSpec((1, P_pool, KH), srow),
        ]
        operands += [k_scales, v_scales]
    if has_cur:
        C = k_cur.shape[1]
        in_specs += [
            pl.BlockSpec((1, C, KH, D), row4),
            pl.BlockSpec((1, C, KH, D), row4),
        ]
        operands += [k_cur, v_cur]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=10,
        grid=(n_cells,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, NH, D), row3),
        scratch_shapes=[
            pltpu.VMEM((R, page_size, KH, D), k_pages.dtype),
            pltpu.VMEM((R, page_size, KH, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((R,)),
            pltpu.SemaphoreType.DMA((R,)),
            pltpu.VMEM((KH, G), jnp.float32),
            pltpu.VMEM((KH, G), jnp.float32),
            pltpu.VMEM((KH, G, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, sm_scale=scale, kv_heads=KH,
        logit_softcap=logit_softcap, has_cur=has_cur, pages_per_block=N,
        prefetch=R, quantized=quantized,
    )
    kv_itemsize = jnp.dtype(k_pages.dtype).itemsize  # 1 for int8 pools
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, NH, D), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * B * NH * D * max_pages * page_size,
            bytes_accessed=(
                2 * max_pages * page_size * KH * D * kv_itemsize * B
                + B * NH * D * 4
            ),
            transcendentals=B * NH * max_pages * page_size,
        ),
    )(
        page_table.astype(jnp.int32), lens32, win, cl, lyr,
        seq_of, blk_of, cells, live_pg.astype(jnp.int32), total_arr,
        *operands,
    )


def ragged_paged_attention_decode_sharded(
    mesh,
    q: jnp.ndarray,          # [B, NH, D], B sharded over dp / NH over tp
    k_pages: jnp.ndarray,    # [P, page_size, KH, D], KH sharded over tp
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray, # [B, max_pages]
    seq_lens: jnp.ndarray,   # [B]
    window=None,
    *,
    sm_scale: float | None = None,
    logit_softcap: float | None = None,
    interpret: bool = False,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    cur_lens: jnp.ndarray | None = None,
    pages_per_block: int | None = None,
    prefetch_pages: int | None = None,
    layer: jnp.ndarray | int | None = None,
    k_scales: jnp.ndarray | None = None,  # [P, KH]/[L, P, KH], KH over tp
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """The decode kernel on a multi-device mesh via manual shard_map.

    GSPMD cannot partition a pallas_call, so the north-star TP config (v5e-8,
    kv heads sharded over tp per shardings.KV_PAGES_SPEC) previously fell
    back to the XLA gather path whose HBM copy the kernel exists to avoid.
    Each (dp, tp) shard runs the kernel on its local batch rows and kv-head
    slice: attention is embarrassingly parallel over both axes (GQA groups
    stay whole because NH and KH divide by tp together), and page indices are
    global pool coordinates valid on every shard.

    sp/ep are ALSO mapped, with no spec mention: decode activations are
    replicated along them (sp shards the token dim of long prefills, ep the
    expert weights — neither shards a 1-token decode), so each (sp, ep)
    shard redundantly computes its (dp, tp) slice. Mapping them manually is
    what keeps GSPMD from trying — and failing — to partition the
    pallas_call along those axes, which is why sp/ep/pp serving configs
    used to regress decode to the XLA gather path (engine/runner.py).
    Under pp this function is called INSIDE the pipeline's shard_map over
    {pp} (parallel/pipeline.py serving_layer_pipeline) with stage-local
    layer pools — nested manual regions over disjoint axes.
    """
    from jax.sharding import PartitionSpec as P

    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else D**-0.5

    has_cur = k_cur is not None
    if has_cur and k_cur.ndim == 3:
        k_cur = k_cur[:, None]  # [B, KH, D] -> C=1 window
        v_cur = v_cur[:, None]
    if has_cur and cur_lens is None:
        cur_lens = jnp.ones(q.shape[:1], jnp.int32)
    quantized = k_scales is not None
    if k_pages.ndim == 4:  # single-layer pools
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        if quantized and k_scales.ndim == 2:
            k_scales = k_scales[None]
            v_scales = v_scales[None]
        layer = 0
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)

    def body(q, kp, vp, pt, lens, l, *rest):
        rest = list(rest)
        ks = vs = None
        if quantized:
            ks, vs = rest[0], rest[1]
            rest = rest[2:]
        kc, vc, cl = rest if has_cur else (None, None, None)
        return ragged_paged_attention_decode(
            q, kp, vp, pt, lens, window,
            sm_scale=scale, logit_softcap=logit_softcap, interpret=interpret,
            k_cur=kc, v_cur=vc, cur_lens=cl,
            pages_per_block=pages_per_block, prefetch_pages=prefetch_pages,
            layer=l[0], k_scales=ks, v_scales=vs,
        )

    head = P("dp", "tp", None)
    pool = P(None, None, None, "tp", None)
    in_specs = [head, pool, pool, P("dp", None), P("dp"), P()]
    operands = [q, k_pages, v_pages, page_table, seq_lens, lyr]
    if quantized:
        # scale slabs shard their KH axis over tp exactly like the pools'
        in_specs += [P(None, None, "tp"), P(None, None, "tp")]
        operands += [k_scales, v_scales]
    if has_cur:
        # the window's KH axis shards over tp like the pool's
        in_specs += [P("dp", None, "tp", None), P("dp", None, "tp", None), P("dp")]
        operands += [k_cur, v_cur, cur_lens]
    # only axes the mesh actually has, and never an axis some caller already
    # made manual (the pp pipeline region). When called inside a manual
    # region the context mesh (with those axes marked Manual) must be the
    # one passed to the nested shard_map, not the concrete mesh.
    from production_stack_tpu.parallel import compat

    manual_already, ctx = compat.current_manual_axes()
    sm_mesh = mesh if not manual_already else ctx
    manual = ({"dp", "tp", "sp", "ep"} & set(mesh.axis_names)) - manual_already
    out = compat.shard_map(
        body,
        sm_mesh,
        axis_names=manual,
        in_specs=tuple(in_specs),
        out_specs=head,
        check=False,
    )(*operands)
    return out
