"""Quantized paged-KV math: int8 pages + per-page (per-kv-head) scales.

The long-context decode step is HBM-bandwidth-bound (docs/benchmarking.md
"Hardware ceilings": page-scattered reads measured 14-30 GB/s vs ~200 GB/s
contiguous), so the only way past the byte wall is fewer bytes per step.
With ``kv_cache_dtype=int8`` the pools store int8 values and a parallel
scales pool holds one fp32 scale per (layer, page, kv-head):

    k_pages, v_pages: [L, P, page_size, KH, D] int8
    k_scales, v_scales: [L, P, KH] float32      (value = q * scale)

This module owns the quantization CONTRACT every consumer must agree on —
the Pallas kernels' in-ring dequant (ops/pallas/*.py), the XLA
fallback/oracle paths (gather_kv_pages_quant here + ops/attention.py), the
decode feedback write (write_kv_pages_all_layers_quant), and the host serde
boundary (kvoffload/serde.py v3 blobs carry the exact pool bytes):

- **Symmetric int8**: ``q = round(x / scale)`` clipped to [-127, 127];
  ``scale = amax / 127`` with an epsilon floor. No zero point — KV
  magnitudes are symmetric and a zero point would cost an add per element
  in the kernels' hot fold.
- **Scale lifecycle (per page, per kv head)**: a page's scale RESETS when
  its slot 0 is written (pages fill front-to-back, so a slot-0 write means
  the slot was reallocated and everything before is garbage — without the
  reset a reused page would inherit the previous owner's amax forever and
  precision would ratchet away). Later appends into a partially-filled
  page may only GROW the scale: ``new = max(old, amax(new_tokens)/127)``,
  and existing int8 content re-quantizes by ``round(q * old/new)`` — a
  no-op when the scale did not grow (ratio 1), and at most 0.5 LSB of
  added error per actual growth event. Growth events are rare in practice
  (KV amax stabilizes within a few tokens), which is what keeps the
  decode-append path's cumulative error bounded.
- **Stale/garbage slots** (beyond ``kv_lens``, or beyond a chunk's end)
  are never dequantized into anything visible: attention masks them the
  same way it masks them for fp pools, and int8 garbage is always finite
  (no NaN*0 hazard, unlike fp garbage).

Everything here is shape-static and scatter-based (``mode='drop'`` on
sentinel indices), so it jits into the existing bucketed programs.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from production_stack_tpu.ops.attention import _kv_flat_indices

# scale floor: an all-zero page still needs a valid (positive) scale so the
# dequant multiply is a no-op rather than a 0*q = 0-with-NaN-risk special case
SCALE_EPS = 1e-8
QMAX = 127.0


# -- device (jnp) ------------------------------------------------------------


def init_kv_scales(num_layers: int, num_pages: int, num_kv_heads: int):
    """Fresh scales pool (ones: garbage pages dequant to small finite noise
    that attention masks anyway; real pages reset their scale on first
    write)."""
    return jnp.ones((num_layers, num_pages, num_kv_heads), jnp.float32)


def dequant(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """q [..., page, KH, D] int8 * scale [..., KH] -> fp."""
    return (q.astype(jnp.float32) * scale[..., None, :, None]).astype(dtype)


def gather_kv_pages_quant(
    k_pages: jnp.ndarray,   # [P, page, KH, D] int8
    v_pages: jnp.ndarray,
    k_scales: jnp.ndarray,  # [P, KH] f32
    v_scales: jnp.ndarray,
    page_table: jnp.ndarray,
    dtype=jnp.float32,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantized twin of ops.attention.gather_kv_pages: gather each
    sequence's pages AND their scales, dequantize to contiguous fp
    [B, S, KH, D] views. The XLA fallback/oracle read path."""
    P, page_size, KH, D = k_pages.shape
    B, max_pages = page_table.shape
    S = max_pages * page_size
    k = dequant(k_pages[page_table], k_scales[page_table], dtype)
    v = dequant(v_pages[page_table], v_scales[page_table], dtype)
    return k.reshape(B, S, KH, D), v.reshape(B, S, KH, D)


def _scatter_max(target_shape, idx, vals):
    """zeros(target_shape).at[:, idx].max(vals) — per-page reductions."""
    return jnp.zeros(target_shape, jnp.float32).at[:, idx].max(vals)


def write_kv_pages_all_layers_quant(
    k_pages: jnp.ndarray,   # [L, P, page, KH, D] int8
    v_pages: jnp.ndarray,
    k_scales: jnp.ndarray,  # [L, P, KH] f32
    v_scales: jnp.ndarray,
    k_new: jnp.ndarray,     # [L, B, T, KH, D] fp
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,  # [B, T] absolute positions; -1 dropped.
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Quantizing twin of ops.attention.write_kv_pages_all_layers — the
    decode feedback write (burst commits, non-fused prefill commits).

    Per the module contract: pages whose slot 0 is written get a fresh
    scale (amax of the new tokens / 127); pages appended mid-page keep
    ``max(old, new)`` and their existing int8 content re-quantizes by the
    scale ratio. Valid positions must be CONTIGUOUS and ascending per row
    (how the scheduler builds every chunk and every burst commit) — the
    re-quant pass gathers each row's touched page window from that
    contract, so it scatters only uniquely-owned pages.
    """
    L, P, page_size, KH, D = k_pages.shape
    B, T = positions.shape
    sentinel = P * page_size
    flat = _kv_flat_indices(page_table, positions, page_size, P)  # [B*T]
    pg = jnp.where(flat < sentinel, flat // page_size, P)         # P = dropped
    slot = flat % page_size
    valid = flat < sentinel

    def per_page_state(x_new, scales):
        x_tok = x_new.reshape(L, B * T, KH, D).astype(jnp.float32)
        amax_tok = jnp.abs(x_tok).max(axis=-1)                    # [L, B*T, KH]
        amax_pg = _scatter_max((L, P + 1, KH), pg, amax_tok)[:, :P]
        want = jnp.maximum(amax_pg / QMAX, SCALE_EPS)
        fresh = (
            jnp.zeros((P + 1,), jnp.float32)
            .at[pg].max((valid & (slot == 0)).astype(jnp.float32))[:P]
            > 0
        )
        touched = (
            jnp.zeros((P + 1,), jnp.float32)
            .at[pg].max(valid.astype(jnp.float32))[:P]
            > 0
        )
        new_scales = jnp.where(
            touched[None, :, None],
            jnp.where(fresh[None, :, None], want, jnp.maximum(scales, want)),
            scales,
        )
        return x_tok, new_scales, touched

    k_tok, k_scales_new, touched = per_page_state(k_new, k_scales)
    v_tok, v_scales_new, _ = per_page_state(v_new, v_scales)

    # touched page windows, per row: positions are contiguous, so row b
    # touches pages [min_pos//page .. max_pos//page] — at most W of them
    W = -(-T // page_size) + 1
    max_pages = page_table.shape[1]
    big = jnp.int32(2**30)
    p0 = jnp.min(jnp.where(positions >= 0, positions, big), axis=1)
    p_last = jnp.max(positions, axis=1)                           # -1 = dead row
    start_pg = jnp.where(p_last >= 0, jnp.minimum(p0, p_last) // page_size, 0)
    jj = jnp.arange(W, dtype=jnp.int32)[None, :]
    logical = start_pg[:, None] + jj                              # [B, W]
    in_range = (
        (p_last >= 0)[:, None]
        & (logical * page_size <= p_last[:, None])
        & (logical < max_pages)
    )
    gids = jnp.take_along_axis(
        page_table, jnp.clip(logical, 0, max_pages - 1), axis=1
    )
    gids_clip = jnp.where(in_range, gids, 0).reshape(-1)          # gather-safe
    gids_scatter = jnp.where(in_range, gids, P).reshape(-1)       # P = dropped

    def requant(pool, old_s, new_s):
        ratio = jnp.where(new_s > 0, old_s / new_s, 1.0)          # [L, P, KH]
        r = ratio[:, gids_clip]                                   # [L, B*W, KH]
        q = pool[:, gids_clip].astype(jnp.float32)                # [L, B*W, pg, KH, D]
        q = jnp.round(q * r[:, :, None, :, None])
        q = jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)
        return pool.at[:, gids_scatter].set(q, mode="drop")

    k_pages = requant(k_pages, k_scales, k_scales_new)
    v_pages = requant(v_pages, v_scales, v_scales_new)

    def scatter_tokens(pool, tok, new_s):
        s_pad = jnp.concatenate(
            [new_s, jnp.full((L, 1, KH), 1.0, jnp.float32)], axis=1
        )
        s_tok = s_pad[:, pg]                                      # [L, B*T, KH]
        q = jnp.round(tok / s_tok[..., None])
        q = jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)
        flat_pool = pool.reshape(L, P * page_size, KH, D)
        flat_pool = flat_pool.at[:, flat].set(q, mode="drop")
        return flat_pool.reshape(pool.shape)

    k_pages = scatter_tokens(k_pages, k_tok, k_scales_new)
    v_pages = scatter_tokens(v_pages, v_tok, v_scales_new)
    return k_pages, v_pages, k_scales_new, v_scales_new


# -- host (numpy): the serde / restore boundary ------------------------------


def quantize_page_host(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """One logical page [L, page, KH, D] fp -> (q int8, scales [L, KH] f32).
    Used when an fp blob restores into a quantized pool (cross-dtype
    warm start / directory pull) and by the v3 serde's generic
    ``serialize``. The page is complete at this point, so the scale is the
    plain amax rule — no growth bookkeeping."""
    xf = np.asarray(x, np.float32)
    amax = np.abs(xf).max(axis=(1, 3))                            # [L, KH]
    scale = np.maximum(amax / QMAX, SCALE_EPS).astype(np.float32)
    q = np.clip(np.round(xf / scale[:, None, :, None]), -QMAX, QMAX)
    return q.astype(np.int8), scale


def dequantize_page_host(
    q: np.ndarray, scale: np.ndarray, dtype=np.float32
) -> np.ndarray:
    """(q [L, page, KH, D] int8, scales [L, KH]) -> fp page."""
    return (
        np.asarray(q, np.float32) * np.asarray(scale, np.float32)[:, None, :, None]
    ).astype(dtype)


def kv_bytes_per_token(
    num_layers: int, num_kv_heads: int, head_dim: int, page_size: int,
    quantized: bool, fp_itemsize: int = 2,
) -> float:
    """KV bytes one token costs the pool (k+v, scales amortized per page) —
    the number the decode byte wall is made of, exported as
    ``vllm:kv_cache_dtype_bytes_per_token``."""
    itemsize = 1 if quantized else fp_itemsize
    per_tok = 2 * num_layers * num_kv_heads * head_dim * itemsize
    if quantized:
        per_tok += 2 * num_layers * num_kv_heads * 4 / max(page_size, 1)
    return float(per_tok)
