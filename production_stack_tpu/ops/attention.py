"""Paged attention for TPU serving — XLA reference implementations.

Design (TPU-first, cf. SURVEY.md §7 "hard parts" #1):

- KV lives in a *page pool* per layer: ``k_pages/v_pages: [num_pages, page_size,
  num_kv_heads, head_dim]`` in HBM. Sequences own pages through an integer
  ``page_table: [batch, max_pages_per_seq]``. All shapes are static under jit;
  the engine buckets batch and context so XLA compiles a handful of programs.
- Writes are flat scatters with ``mode='drop'`` so padded tokens vanish without
  branches (no dynamic control flow inside jit).
- Attention is an online-softmax ("flash") computation scanned over KV blocks,
  GQA-aware (einsum over grouped heads, no materialized head repeat). The same
  code path serves chunked prefill (T tokens against S context) and decode
  (T=1); decode first gathers the sequence's pages into a contiguous [B, S]
  view. A Pallas kernel that streams pages HBM->VMEM without the gather
  replaces this on TPU (ops/pallas/paged_attention.py); this module is the
  always-correct fallback and the unit-test oracle.

Reference behavior being matched: vLLM's PagedAttention + chunked prefill as
configured by the reference stack (helm/templates/deployment-vllm-multi.yaml:128-141
in /root/reference — the stack enables chunked prefill and prefix caching; the
engine must make those real).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _kv_flat_indices(
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    page_size: int,
    num_pages: int,
) -> jnp.ndarray:
    """Flat pool-slot index per token ([B*T]); invalid tokens (padding, or
    positions beyond the owned pages) route to the out-of-range sentinel
    ``num_pages * page_size`` so scatters drop them."""
    B, T = positions.shape
    max_pages = page_table.shape[1]
    page_idx = positions // page_size
    slot = positions % page_size
    phys = jnp.take_along_axis(
        page_table, jnp.clip(page_idx, 0, max_pages - 1), axis=1
    )
    flat = phys * page_size + slot
    valid = (positions >= 0) & (page_idx < max_pages)
    return jnp.where(valid, flat, num_pages * page_size).reshape(-1)


def stale_kv_positions(
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    page_size: int,
) -> jnp.ndarray:
    """KV-slot positions for write-after-attend attention: paged slot j holds
    absolute position j while j < the chunk start (slots at/after it are
    stale — the current chunk's K/V ride in-register), then the chunk's own
    positions. Returns [B, S + T] for flash_attention(kv_positions=...)."""
    S = page_table.shape[1] * page_size
    chunk_start = jnp.maximum(positions[:, 0], 0)
    slot_pos = jnp.arange(S, dtype=jnp.int32)[None, :]
    paged_pos = jnp.where(slot_pos < chunk_start[:, None], slot_pos, -1)
    return jnp.concatenate([paged_pos, positions], axis=1)


def burst_kv_positions(
    kv_lens: jnp.ndarray,   # [B] total length incl. the current token
    cur_lens: jnp.ndarray,  # [B] in-register window entries (1..C)
    S: int,                 # paged slots (max_pages * page_size)
    C: int,                 # window capacity
) -> jnp.ndarray:
    """KV-slot positions for deferred-burst attention, [B, S + C]: paged
    slot j holds absolute position j while j < kv_lens - cur_lens (the
    stale boundary — later slots' K/V live in the window instead), and
    window entry j holds position ``kv_lens - cur_lens + j`` for
    j < cur_lens. Shared by the XLA oracle (paged_attention_decode), the
    model fallbacks, and mirrored by the Pallas kernel's masking — keep
    them in lockstep."""
    paged_end = kv_lens - cur_lens
    slot = jnp.arange(S, dtype=jnp.int32)[None, :]
    paged_pos = jnp.where(slot < paged_end[:, None], slot, -1)
    j = jnp.arange(C, dtype=jnp.int32)[None, :]
    win_pos = jnp.where(j < cur_lens[:, None], paged_end[:, None] + j, -1)
    return jnp.concatenate([paged_pos, win_pos], axis=1)


def write_kv_pages(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V tokens into the page pool.

    Args:
      k_pages, v_pages: [P, page_size, KH, D] page pools.
      k_new, v_new:     [B, T, KH, D] fresh keys/values for this step.
      page_table:       [B, max_pages] int32 page ids owned by each sequence.
      positions:        [B, T] int32 absolute token positions; -1 marks padding
                        (those writes are dropped).

    Returns updated (k_pages, v_pages). Callers should donate the pools so XLA
    updates them in place.
    """
    P, page_size, KH, D = k_pages.shape
    B, T = positions.shape
    flat = _kv_flat_indices(page_table, positions, page_size, P)
    k_flat = k_pages.reshape(P * page_size, KH, D)
    v_flat = v_pages.reshape(P * page_size, KH, D)
    k_flat = k_flat.at[flat].set(k_new.reshape(B * T, KH, D), mode="drop")
    v_flat = v_flat.at[flat].set(v_new.reshape(B * T, KH, D), mode="drop")
    return k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape)


def write_kv_pages_all_layers(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One batched scatter writing EVERY layer's fresh K/V into the stacked
    pools (write-after-attend mode).

    Why: per-layer pool-slice updates inside the layer scan force XLA to
    materialize pool-sized copies each iteration (profiled at ~half the
    decode step on v5e). Writing once, outside the scan, with the pools
    donated, updates in place.

    Args:
      k_pages, v_pages: [L, P, page_size, KH, D] stacked pools.
      k_new, v_new:     [L, B, T, KH, D] per-layer fresh keys/values.
      page_table:       [B, max_pages] int32.
      positions:        [B, T] int32 absolute positions; -1 dropped.
    """
    L, P, page_size, KH, D = k_pages.shape
    B, T = positions.shape
    flat = _kv_flat_indices(page_table, positions, page_size, P)
    k_flat = k_pages.reshape(L, P * page_size, KH, D)
    v_flat = v_pages.reshape(L, P * page_size, KH, D)
    k_flat = k_flat.at[:, flat].set(k_new.reshape(L, B * T, KH, D), mode="drop")
    v_flat = v_flat.at[:, flat].set(v_new.reshape(L, B * T, KH, D), mode="drop")
    return k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape)


def gather_kv_pages(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Gather each sequence's pages into contiguous [B, S, KH, D] views,
    S = max_pages * page_size (bucketed by the scheduler)."""
    P, page_size, KH, D = k_pages.shape
    B, max_pages = page_table.shape
    k = k_pages[page_table]  # [B, max_pages, page_size, KH, D]
    v = v_pages[page_table]
    S = max_pages * page_size
    return k.reshape(B, S, KH, D), v.reshape(B, S, KH, D)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_lens: jnp.ndarray,
    *,
    sm_scale: float | None = None,
    block_size: int = 512,
    window: int | None = None,
    logit_softcap: float | None = None,
    kv_positions: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Online-softmax attention scanned over KV blocks (GQA-aware).

    Args:
      q:           [B, T, NH, D] queries (chunk of T tokens; T=1 for decode).
      k, v:        [B, S, KH, D] contiguous keys/values (gathered pages).
      q_positions: [B, T] absolute position of each query token; -1 = padding.
      kv_lens:     [B] valid KV length per sequence.
      sm_scale:    softmax scale; defaults to D**-0.5.
      block_size:  KV block per scan step (memory/compute tradeoff).
      window:      sliding-window size (Mistral-style): query at position p sees
                   KV positions (p - window, p]. None = full causal.
      kv_positions: optional [B, S] absolute position of each KV slot, -1 =
                   invalid. When given, visibility is (pos >= 0) & (pos <=
                   q_pos) & window, and ``kv_lens`` is ignored — this lets
                   callers attend over a concatenation of paged KV (slot j at
                   position j) and in-register current-chunk K/V (write-after-
                   attend mode: the pool is stale for the current chunk).

    Returns [B, T, NH, D] in q.dtype. Without kv_positions, KV index j is
    visible to a query at position p iff j <= p and j < kv_len.
    """
    B, T, NH, D = q.shape
    S, KH = k.shape[1], k.shape[2]
    G = NH // KH
    scale = sm_scale if sm_scale is not None else D**-0.5

    bs = min(block_size, S)
    num_blocks = -(-S // bs)
    pad = num_blocks * bs - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if kv_positions is not None:
            kv_positions = jnp.pad(
                kv_positions, ((0, 0), (0, pad)), constant_values=-1
            )

    qf = (q.astype(jnp.float32) * scale).reshape(B, T, KH, G, D)
    kb = k.reshape(B, num_blocks, bs, KH, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, num_blocks, bs, KH, D).transpose(1, 0, 2, 3, 4)
    pb = (
        None
        if kv_positions is None
        else kv_positions.reshape(B, num_blocks, bs).transpose(1, 0, 2)
    )

    m0 = jnp.full((B, T, KH, G), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, T, KH, G), jnp.float32)
    acc0 = jnp.zeros((B, T, KH, G, D), jnp.float32)

    def body(carry, inputs):
        m, l, acc, start = carry[0], carry[1], carry[2], carry[3]
        if pb is None:
            kblk, vblk = inputs
        else:
            kblk, vblk, posblk = inputs
        kf = kblk.astype(jnp.float32)
        scores = jnp.einsum("btkgd,bskd->btkgs", qf, kf)  # [B,T,KH,G,bs]
        if logit_softcap is not None:
            # Gemma-2: soft-bound scores to (-cap, cap) before masking
            scores = logit_softcap * jnp.tanh(scores / logit_softcap)
        if pb is None:
            idx = start + jnp.arange(bs)
            visible = (idx[None, None, :] <= q_positions[:, :, None]) & (
                idx[None, None, :] < kv_lens[:, None, None]
            )  # [B, T, bs]
            if window is not None:
                visible &= idx[None, None, :] > q_positions[:, :, None] - window
        else:
            pos = posblk[:, None, :]  # [B, 1, bs]
            visible = (pos >= 0) & (pos <= q_positions[:, :, None])
            if window is not None:
                visible &= pos > q_positions[:, :, None] - window
        scores = jnp.where(visible[:, :, None, None, :], scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Guard exp(NEG_INF - NEG_INF) for fully masked rows.
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(visible[:, :, None, None, :], p, 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "btkgs,bskd->btkgd", p, vblk.astype(jnp.float32)
        )
        return (m_new, l_new, acc_new, start + bs), None

    xs = (kb, vb) if pb is None else (kb, vb, pb)
    (m, l, acc, _), _ = lax.scan(body, (m0, l0, acc0, jnp.int32(0)), xs)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(B, T, NH, D).astype(q.dtype)


def paged_attention_decode(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    seq_lens: jnp.ndarray,
    *,
    sm_scale: float | None = None,
    window=None,
    logit_softcap: float | None = None,
    k_cur: jnp.ndarray | None = None,   # [B, C, KH, D] in-register burst K/V
    v_cur: jnp.ndarray | None = None,
    cur_lens: jnp.ndarray | None = None,  # [B] valid window entries (1..C)
    k_scales: jnp.ndarray | None = None,  # [P, KH] f32 (int8 pools, ops/quant.py)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Decode-step attention: one query token per sequence against its pages.

    q: [B, NH, D]; returns [B, NH, D]. XLA reference path (gather + flash);
    the Pallas kernel streams pages directly and skips the gather.

    With ``k_cur/v_cur`` (write-after-attend), pool slots at positions >=
    seq_lens - cur_lens are stale; window entry j holds the token at
    absolute position ``seq_lens - cur_lens + j`` (valid for j < cur_lens).
    A fused decode burst defers ALL its KV scatters this way: the pool stays
    read-only through the burst and the accumulated burst tokens ride in the
    window (runner._multi_step_fn).

    With ``k_scales/v_scales`` the pools are int8 and the gather
    dequantizes (ops/quant.py contract) — the oracle for the kernel's
    in-ring dequant; ``k_cur/v_cur`` stay fp.
    """
    if k_scales is not None:
        from production_stack_tpu.ops.quant import gather_kv_pages_quant

        k, v = gather_kv_pages_quant(
            k_pages, v_pages, k_scales, v_scales, page_table, dtype=q.dtype
        )
    else:
        k, v = gather_kv_pages(k_pages, v_pages, page_table)
    if k_cur is not None:
        B, C = k_cur.shape[0], k_cur.shape[1]
        if cur_lens is None:
            cur_lens = jnp.ones((B,), jnp.int32)
        kv_positions = burst_kv_positions(seq_lens, cur_lens, k.shape[1], C)
        k = jnp.concatenate([k, k_cur.astype(k.dtype)], axis=1)
        v = jnp.concatenate([v, v_cur.astype(v.dtype)], axis=1)
    else:
        kv_positions = None
    out = flash_attention(
        q[:, None],
        k,
        v,
        q_positions=(seq_lens - 1)[:, None],
        kv_lens=seq_lens,
        sm_scale=sm_scale,
        window=window,
        logit_softcap=logit_softcap,
        kv_positions=kv_positions,
    )
    return out[:, 0]
