"""Normalization ops.

TPU notes: RMSNorm is a pure VPU op; we compute the variance in float32 regardless
of activation dtype (bf16 accumulation loses too much precision at hidden>=4096) and
let XLA fuse the rsqrt+scale into neighbouring elementwise ops.
"""

from __future__ import annotations

import jax.lax as lax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm: x * rsqrt(mean(x^2) + eps) * weight, variance in fp32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    """Standard LayerNorm (used by the OPT family), stats in fp32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    normed = (xf - mean) * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
