"""Shared graceful-termination scaffolding for the server mains.

Both the engine API server and the router drain on SIGTERM/SIGINT (K8s pod
rotation); the signal choreography — install handlers, wake on the first
signal, deregister so a SECOND Ctrl-C/SIGTERM gets default handling (force
quit) — is identical and easy to let drift, so it lives here once.
"""

from __future__ import annotations

import asyncio
import signal


async def wait_for_termination() -> None:
    """Block until the first SIGTERM/SIGINT. The handlers deregister
    themselves on delivery, so a repeat signal force-quits instead of
    re-setting an already-set event."""
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def on_signal():
        stop.set()
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.remove_signal_handler(s)
            except (NotImplementedError, ValueError):
                pass

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, on_signal)
        except NotImplementedError:  # non-unix
            pass
    await stop.wait()
