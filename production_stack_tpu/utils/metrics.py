"""Prometheus histogram support for the serving metrics endpoints.

The reference dashboard's headline panels are TTFT *distribution* and
request-latency *distribution* heatmaps over `vllm:time_to_first_token_seconds`
and `vllm:e2e_request_latency_seconds` histogram buckets
(/root/reference/observability/vllm-dashboard.json:34-1312); gauges and
quantile snapshots cannot back those panels. This module provides the
cumulative bucket counters both the engine API server and the router export.

Bucket boundaries mirror vLLM's metric definitions so the reference
dashboard's queries work unchanged against our `/metrics`.
"""

from __future__ import annotations

import threading

# vLLM's TTFT histogram boundaries (seconds)
TTFT_BUCKETS = (
    0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1, 0.25, 0.5, 0.75,
    1.0, 2.5, 5.0, 7.5, 10.0,
)
# vLLM's e2e request-latency boundaries (seconds)
LATENCY_BUCKETS = (
    0.3, 0.5, 0.8, 1.0, 1.5, 2.0, 2.5, 5.0, 10.0, 15.0, 20.0, 30.0,
    40.0, 50.0, 60.0,
)


class Histogram:
    """Cumulative Prometheus histogram (thread-safe observe + render)."""

    def __init__(self, name: str, buckets: tuple, help_: str = ""):
        self.name = name
        self.help = help_ or name
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
            self._sum += value
            self._total += 1

    def reset(self) -> None:
        """Debug/bench only (the /metrics/reset endpoint): live Prometheus
        counters must never reset outside a process restart."""
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._total = 0

    def render(self, labels: str) -> list[str]:
        """Prometheus exposition lines; ``labels`` like 'model_name="m"'."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._total, self._sum
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            le = f"{b:g}"
            lines.append(f'{self.name}_bucket{{{labels},le="{le}"}} {cum}')
        lines.append(f'{self.name}_bucket{{{labels},le="+Inf"}} {total}')
        lines.append(f"{self.name}_count{{{labels}}} {total}")
        lines.append(f"{self.name}_sum{{{labels}}} {s}")
        return lines
