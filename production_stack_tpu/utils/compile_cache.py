"""Persistent XLA compilation cache wiring.

Cold compiles on a network-attached TPU cost 20-40 s per program variant;
a serving engine compiles dozens of (batch bucket, pages bucket) shapes at
startup. The reference stack never pays this (vLLM ships precompiled CUDA
kernels); the TPU-native equivalent is JAX's persistent compilation cache,
which serves every repeat compile from disk — across engine restarts, test
runs, and bench invocations.

Called from engine startup (engine/engine.py), the test harness
(tests/conftest.py), and bench.py. In Kubernetes the cache directory is a
PVC mounted into the engine pod (helm/templates/deployment-engine.yaml) so
restarts and same-model replicas skip straight to warm starts.
"""

from __future__ import annotations

import hashlib
import os
import sys

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

_DEFAULT_DIR = os.path.join(
    os.environ.get("PSTPU_CACHE_ROOT", os.path.expanduser("~/.cache")),
    "production_stack_tpu",
    "xla_cache",
)

_enabled_dir: str | None = None
# the UNSCOPED base the enabled dir was derived from: later scoped calls
# must re-derive from this, never from the already-scoped result
_base_dir: str | None = None


def _cpu_feature_scope() -> str:
    """Subdirectory name isolating XLA:CPU AOT entries by writer configuration.

    XLA:CPU serializes executables as AOT results whose embedded machine
    features must match the loading process exactly; a mismatch (different
    host ISA, jaxlib, or tuning flags flipped by co-loaded frameworks such
    as TensorFlow/torch initializing LLVM differently) makes
    cpu_aot_loader.cc reject — or worse, mis-accept — every entry. Keying
    the directory on those inputs means a process only ever reads entries
    written by an identically-configured process.
    """
    import jax

    parts = [
        jax.__version__,
        getattr(jax, "lib", None) and getattr(jax.lib, "__version__", "") or "",
        os.environ.get("XLA_FLAGS", ""),
        ",".join(sorted(m for m in ("tensorflow", "torch") if m in sys.modules)),
    ]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    parts.append(line.strip())
                    break
    except OSError:
        import platform

        parts.append(platform.processor() or platform.machine())
    digest = hashlib.sha1("\n".join(parts).encode()).hexdigest()[:12]
    return f"cpu-{digest}"


def enable_persistent_cache(
    cache_dir: str | None = None, scope: str | None = None
) -> str | None:
    """Point JAX's compilation cache at a persistent directory. Idempotent.

    Resolution order: explicit arg > $PSTPU_COMPILE_CACHE_DIR > JAX's own
    $JAX_COMPILATION_CACHE_DIR (left untouched if set) > ~/.cache default.
    Set PSTPU_COMPILE_CACHE_DIR=off to disable. Returns the directory in
    effect, or None when disabled.

    ``scope`` appends a subdirectory — multi-host serving passes its process
    topology (engine/engine.py): an executable compiled for one topology
    must never be served to another (same device ids, different process
    boundaries — observed to hang the jax.distributed rendezvous), and
    per-process subdirs also keep concurrent writers apart.
    """
    global _enabled_dir, _base_dir
    import jax

    env = os.environ.get("PSTPU_COMPILE_CACHE_DIR")
    cache_dir = cache_dir or env
    if cache_dir in ("off", "none", "0"):
        return None
    if cache_dir is None:
        # respect a cache dir the operator already configured via JAX's env
        cache_dir = jax.config.jax_compilation_cache_dir
        if cache_dir is not None and cache_dir == _enabled_dir:
            # already wired by an earlier call in this process (conftest,
            # bench, a previous engine): the configured dir is the SCOPED
            # result, and re-scoping it would nest cpu-<digest> subdirs one
            # level deeper per engine construction — every engine then
            # compiles against a brand-new empty cache (observed: a
            # 23-level-deep .cache/xla chain and a tier-1 suite that
            # recompiled cold for every LLMEngine test)
            if not scope:
                return _enabled_dir
            # a scoped request (multi-host topology) must derive from the
            # ORIGINAL base, not the already-scoped result
            if _base_dir is not None:
                cache_dir = _base_dir
    if cache_dir is None:
        # Default-on only for TPU backends, where a cold compile costs
        # 20-40 s per program. XLA:CPU AOT cache loads are NOT robust: an
        # entry written by a process with different CPU tuning features
        # (e.g. TensorFlow loaded via sentence-transformers flips
        # prefer-no-scatter/-gather) fails the loader's machine check and
        # can spin for minutes per entry — observed hanging engine startup.
        # CPU users opt in with an explicit dir (tests/conftest.py does).
        if jax.default_backend() != "tpu":
            return None
        cache_dir = _DEFAULT_DIR
    _base_dir = cache_dir
    if scope:
        cache_dir = os.path.join(cache_dir, scope)
    try:
        if jax.default_backend() == "cpu":
            # Explicitly-enabled CPU caches (tests, dryruns) get a
            # writer-config scope so feature-mismatched AOT entries are never
            # even offered to the loader (see _cpu_feature_scope).
            cache_dir = os.path.join(cache_dir, _cpu_feature_scope())
    except Exception as e:  # noqa: BLE001 - no backend yet: don't risk a shared dir
        logger.warning("compilation cache disabled (%s: %s)", type(e).__name__, e)
        return None
    if _enabled_dir == cache_dir:
        return _enabled_dir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds (1 s / 0 bytes) skip exactly the small programs
        # whose compiles add up across a 150-test suite — cache everything
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _enabled_dir = cache_dir
        logger.info("persistent XLA compilation cache at %s", cache_dir)
    except Exception as e:  # noqa: BLE001 - cache is an optimization, never fatal
        logger.warning("compilation cache disabled (%s: %s)", type(e).__name__, e)
        return None
    return _enabled_dir
