"""Colored per-module logger (parity with the reference's vllm_router/log.py)."""

from __future__ import annotations

import logging
import os
import sys

_COLORS = {
    logging.DEBUG: "\x1b[36m",
    logging.INFO: "\x1b[32m",
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[41m",
}
_RESET = "\x1b[0m"


class _Formatter(logging.Formatter):
    def __init__(self, use_color: bool):
        super().__init__()
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        base = (
            f"[{self.formatTime(record, '%Y-%m-%d %H:%M:%S')}] "
            f"{record.levelname} {record.name}: {record.getMessage()}"
        )
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        if self.use_color:
            color = _COLORS.get(record.levelno, "")
            return f"{color}{base}{_RESET}"
        return base


def init_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_Formatter(use_color=sys.stderr.isatty()))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("PSTPU_LOG_LEVEL", "INFO").upper())
        logger.propagate = False
    return logger
