"""Wire protocol shared by the KV cache server and the KV-index controller.

One frame = ``u32 header_len | u32 payload_len | header JSON | payload bytes``.
The header carries the op and metadata; the payload carries KV blobs. This is
the TPU stack's replacement for the two native protocols the reference leans
on: the LMCache remote-server TCP protocol
(/root/reference helm/templates/deployment-cache-server.yaml:33-43) and the
LMCache controller ZMQ protocol (/root/reference
src/vllm_router/routers/routing_logic.py:228-252).

Async (server / router) and blocking (engine worker thread) endpoints speak
the same frames.
"""

from __future__ import annotations

import asyncio
import json
import socket
import struct
from typing import Optional

_FRAME = struct.Struct("!II")
MAX_HEADER = 16 << 20
MAX_PAYLOAD = 1 << 30


def pack(header: dict, payload: bytes = b"") -> bytes:
    hdr = json.dumps(header).encode()
    return _FRAME.pack(len(hdr), len(payload)) + hdr + payload


# -- asyncio endpoint ---------------------------------------------------------


async def read_frame(reader: asyncio.StreamReader) -> tuple[dict, bytes]:
    raw = await reader.readexactly(_FRAME.size)
    hlen, plen = _FRAME.unpack(raw)
    if hlen > MAX_HEADER or plen > MAX_PAYLOAD:
        raise ValueError(f"oversized frame: header={hlen} payload={plen}")
    hdr = json.loads(await reader.readexactly(hlen)) if hlen else {}
    payload = await reader.readexactly(plen) if plen else b""
    return hdr, payload


async def write_frame(
    writer: asyncio.StreamWriter, header: dict, payload: bytes = b""
) -> None:
    writer.write(pack(header, payload))
    await writer.drain()


# -- blocking endpoint (engine-side worker thread) ----------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class BlockingClient:
    """Request/response client over one persistent connection; reconnects
    lazily after errors. Not thread-safe — each worker thread owns one."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock: Optional[socket.socket] = None

    @classmethod
    def from_url(cls, url: str, **kw) -> "BlockingClient":
        host, port = parse_hostport(url)
        return cls(host, port, **kw)

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def request(self, header: dict, payload: bytes = b"") -> tuple[dict, bytes]:
        try:
            sock = self._connect()
            sock.sendall(pack(header, payload))
            hlen, plen = _FRAME.unpack(_recv_exact(sock, _FRAME.size))
            if hlen > MAX_HEADER or plen > MAX_PAYLOAD:
                raise ValueError("oversized frame")
            hdr = json.loads(_recv_exact(sock, hlen)) if hlen else {}
            body = _recv_exact(sock, plen) if plen else b""
            return hdr, body
        except Exception:
            self.close()
            raise


def parse_hostport(url: str, default_port: int = 0) -> tuple[str, int]:
    """'host:port', 'tcp://host:port' or 'http://host:port' -> (host, port)."""
    if "://" in url:
        url = url.split("://", 1)[1]
    url = url.rstrip("/")
    if ":" in url:
        host, port = url.rsplit(":", 1)
        return host, int(port)
    return url, default_port
