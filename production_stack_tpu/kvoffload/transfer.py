"""Disaggregated-prefill KV transfer: prefill (producer) -> decode (consumer).

TPU-native replacement for the reference's NIXL/UCX sender/receiver pair
(/root/reference helm/templates/deployment-vllm-multi.yaml:256-296:
`LMCACHE_ENABLE_NIXL`, `LMCACHE_NIXL_ROLE=sender/receiver`, receiver port
55555; examples/disaggregated_prefill/pd.yaml:22-65). No GPU-direct fabric on
TPU pods — KV pages ship as serde blobs over TCP (DCN between pods; loopback
within one) keyed by the same rolling chunk hashes the prefix cache uses, so
the decode engine's ordinary offload-restore path injects them into HBM.

Flow (two engines + router request_service.route_disaggregated_prefill_request):
1. Router sends the prompt to the prefill engine with max_tokens=1.
2. Producer engine, at sequence finish and *before* answering the prefill
   HTTP request, pushes each full page's blob to the consumer's receiver —
   so the KV is already there when the router's phase-2 decode request lands.
3. Consumer's receiver drops blobs into its offload store; decode admission
   restores them via KVPageManager.match_prefix (offload extension path).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from production_stack_tpu.kvoffload.protocol import (
    BlockingClient,
    parse_hostport,
    read_frame,
    write_frame,
)
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class KVTransferReceiver:
    """TCP server inside the decode (consumer) engine process; pushes land in
    the engine's tiered store where prefix-match admission finds them."""

    def __init__(
        self,
        store,
        host: str = "0.0.0.0",
        port: int = 55555,
        device_endpoint=None,
        staging=None,
    ):
        self.store = store
        self.host, self.port = host, port
        # device-to-device mode (DeviceKVEndpoint + DeviceStaging): producers
        # announce pages via "page_ready" and we pull them device->device
        self.device_endpoint = device_endpoint
        self.staging = staging
        self.received_chunks = 0
        self.received_bytes = 0
        self.device_pages = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.bound_port: Optional[int] = None

    async def _handle(self, reader, writer):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = hdr.get("op")
                if op == "push":
                    self.store.put_local(hdr["key"], payload)
                    self.received_chunks += 1
                    self.received_bytes += len(payload)
                    await write_frame(writer, {"ok": True})
                elif op == "page_query":
                    # device path phase 1: atomically reserve staging budget
                    # so the producer registers the page with its transfer
                    # server only once a pull is guaranteed to be attempted
                    if self.device_endpoint is None or self.staging is None:
                        await write_frame(writer, {"ok": False})
                    else:
                        verdict = self.staging.reserve(
                            hdr["key"], int(hdr["nbytes"])
                        )
                        await write_frame(writer, {
                            "ok": verdict == "reserved",
                            "have": verdict == "have",
                        })
                elif op == "page_ready":
                    # device path phase 2: pull the registered page
                    # device->device and stage it for admission
                    ok = False
                    if self.device_endpoint is not None and self.staging is not None:
                        try:
                            k_dev, v_dev = await asyncio.to_thread(
                                self.device_endpoint.pull,
                                hdr["addr"], hdr["uuid"],
                                hdr["shape"], hdr["dtype"],
                            )
                            self.staging.put(hdr["key"], k_dev, v_dev)
                            self.device_pages += 1
                            ok = True
                        except Exception as e:  # noqa: BLE001
                            self.staging.unreserve(hdr["key"])
                            logger.warning("device kv pull failed: %s", e)
                    await write_frame(writer, {"ok": ok})
                elif op == "ping":
                    await write_frame(writer, {"ok": True})
                else:
                    await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})
        except Exception as e:
            logger.warning("kv receiver: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def start(self) -> None:
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def serve():
                server = await asyncio.start_server(self._handle, self.host, self.port)
                self.bound_port = server.sockets[0].getsockname()[1]
                self._started.set()
                async with server:
                    await server.serve_forever()

            try:
                self._loop.run_until_complete(serve())
            except asyncio.CancelledError:
                pass

        self._thread = threading.Thread(target=run, daemon=True, name="kv-receiver")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("KV transfer receiver failed to start")
        logger.info("kv transfer receiver on %s:%s", self.host, self.bound_port)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        if self._thread is not None:
            self._thread.join(timeout=5)


class KVTransferSender:
    """Producer-side pusher. Called on the engine device thread at sequence
    finish — synchronous by design: the prefill HTTP response must not return
    before the decode peer holds the KV (the reference gets the same ordering
    from the NIXL blocking handshake)."""

    def __init__(self, peer_url: str, timeout: float = 30.0, device_endpoint=None):
        host, port = parse_hostport(peer_url, default_port=55555)
        self._client = BlockingClient(host, port, timeout=timeout)
        self._lock = threading.Lock()
        self.device_endpoint = device_endpoint
        self.sent_chunks = 0
        self.sent_bytes = 0
        self.device_pages = 0
        self.skipped_pages = 0
        self.errors = 0

    def push_device(self, key: str, nbytes: int, make_arrays) -> bool:
        """Ship a page device->device; the final ACK doubles as the
        NIXL-style completion handshake (the prefill HTTP response must not
        return before the consumer holds the KV).

        Two phases: "page_query" asks the consumer to reserve staging budget
        BEFORE anything is gathered or registered — the XLA API has no cancel
        for await_pull, so a refused offer must never register (a
        registered-then-unpulled page would pin its device buffers), and
        ``make_arrays()`` (the producer's single-device page gather) only
        runs once the consumer has said yes.
        Returns False so the caller can fall back to a TCP blob push."""
        if self.device_endpoint is None:
            return False
        uuid = None
        try:
            with self._lock:
                hdr, _ = self._client.request(
                    {"op": "page_query", "key": key, "nbytes": nbytes}
                )
                if hdr.get("have"):
                    # consumer already STAGED this page (shared prefix) —
                    # nothing to ship, and no TCP fallback either
                    self.skipped_pages += 1
                    return True
                if not hdr.get("ok"):
                    return False  # staging full / device mode off on peer
                k_dev, v_dev = make_arrays()
                uuid, shape, dtype = self.device_endpoint.offer(k_dev, v_dev)
                hdr, _ = self._client.request({
                    "op": "page_ready", "key": key, "uuid": uuid,
                    "shape": shape, "dtype": dtype,
                    "addr": self.device_endpoint.address,
                })
            ok = bool(hdr.get("ok"))
            self.device_endpoint.release(uuid, pulled=ok)
            uuid = None
            if ok:
                self.device_pages += 1
                return True
            return False
        except Exception as e:  # noqa: BLE001
            self.errors += 1
            logger.warning("device kv offer failed: %s", e)
            return False
        finally:
            if uuid is not None:
                self.device_endpoint.release(uuid, pulled=False)

    def push(self, key: str, blob: bytes) -> bool:
        with self._lock:
            try:
                hdr, _ = self._client.request({"op": "push", "key": key}, blob)
                if hdr.get("ok"):
                    self.sent_chunks += 1
                    self.sent_bytes += len(blob)
                    return True
                return False
            except Exception as e:
                self.errors += 1
                logger.warning("kv transfer push failed: %s", e)
                return False

    def close(self) -> None:
        self._client.close()


# -- device-to-device path (co-located prefill/decode slices) -----------------


class DeviceKVEndpoint:
    """One engine's side of the jax device-to-device KV fabric.

    Wraps ``jax.experimental.transfer``: the producer registers page arrays
    for pull (``offer``); the consumer pulls them straight into its own
    devices (``pull``) — KV moves device->device over the XLA transfer
    service (ICI/DCN on TPU pods) with no host serde round trip. This is the
    stack's NIXL-GPU-direct analogue (reference
    deployment-vllm-multi.yaml:256-296) for slices that share a host or
    fabric; the TCP blob path remains the cross-pod fallback.
    """

    def __init__(self, runner, host: str = "127.0.0.1"):
        import jax
        from jax.experimental import transfer

        self.runner = runner
        client = runner.mesh.devices.flat[0].client
        self._server = transfer.start_transfer_server(
            client, f"{host}:0", [f"{host}:0"]
        )
        self.address = self._server.address()
        self._conns: dict = {}
        self._offered: dict[int, tuple] = {}  # uuid -> arrays (kept alive)
        self._uuid = 0
        self._lock = threading.Lock()
        self.offered_pages = 0
        self.pulled_pages = 0
        self.leaked_offers = 0

    def offer(self, k_dev, v_dev) -> tuple[int, list, list]:
        """Register a page's device K/V for remote pull. Returns
        (uuid, shape, dtype-name); the arrays stay referenced until
        ``release``."""
        with self._lock:
            uuid = self._uuid
            self._uuid += 1
            self._offered[uuid] = (k_dev, v_dev)
        self._server.await_pull(uuid, [k_dev, v_dev])
        self.offered_pages += 1
        return uuid, list(k_dev.shape), str(k_dev.dtype)

    def release(self, uuid: int, pulled: bool = True) -> None:
        """Drop our reference to an offered page. LIMITATION: the XLA API has
        no await_pull cancel, so if the peer never pulled, the transfer
        server's own registration (and the page's device buffers) persist
        until this endpoint is closed — tracked in ``leaked_offers`` and
        bounded in practice because offers only outlive their pull on
        transient pull errors (refusals never register; see push_device)."""
        with self._lock:
            if self._offered.pop(uuid, None) is not None and not pulled:
                self.leaked_offers += 1
                logger.warning(
                    "unpulled transfer offer %d leaks one page of device "
                    "memory until shutdown (%d total)", uuid, self.leaked_offers,
                )

    def pull(self, addr: str, uuid: int, shape, dtype):
        """Pull a page's (k, v) device arrays from the producer at ``addr``."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            conn = self._conns.get(addr)
            if conn is None:
                conn = self._server.connect(addr)
                self._conns[addr] = conn
        dev = self.runner.mesh.devices.flat[0]
        sds = jax.ShapeDtypeStruct(
            tuple(shape), jnp.dtype(dtype),
            sharding=jax.sharding.SingleDeviceSharding(dev),
        )
        k_dev, v_dev = conn.pull(uuid, [sds, sds])
        self.pulled_pages += 1
        return k_dev, v_dev

    def close(self) -> None:
        """Drop connections and any still-offered arrays. The XLA API has no
        transfer-server shutdown; releasing the Python references lets the
        server object (and its device buffers) be collected with us."""
        with self._lock:
            self._conns.clear()
            self._offered.clear()
        self._server = None


class DeviceStaging:
    """Consumer-side staging for device-pulled pages awaiting admission.

    Pulled pages live on device until the decode request's prefix match
    injects them into the pool (runner.set_page — a device->device copy).
    Bounded and self-cleaning: budget is reserved atomically BEFORE the pull
    (so concurrent producers cannot overcommit), and both reservations and
    staged pages expire after ``ttl`` seconds — a decode request that never
    arrives (client abort after prefill) must not pin consumer HBM or wedge
    the budget into permanent TCP fallback."""

    def __init__(self, max_bytes: int = 1 << 30, ttl: float = 120.0):
        import time as time_mod

        self._time = time_mod.monotonic
        self.max_bytes = max_bytes
        self.ttl = ttl
        self._pages: dict[str, tuple] = {}      # key -> (k, v, deadline)
        self._reserved: dict[str, tuple] = {}   # key -> (nbytes, deadline)
        self._bytes = 0
        self._lock = threading.Lock()
        self.expired_pages = 0

    def _sweep_locked(self) -> None:
        now = self._time()
        for key in [k for k, (_, _, d) in self._pages.items() if d < now]:
            k_dev, _, _ = self._pages.pop(key)
            self._bytes -= int(k_dev.nbytes) * 2
            self.expired_pages += 1
        for key in [k for k, (_, d) in self._reserved.items() if d < now]:
            nbytes, _ = self._reserved.pop(key)
            self._bytes -= nbytes

    def reserve(self, key: str, nbytes: int) -> str:
        """Atomically check-and-reserve budget for an incoming page.
        Returns "reserved", "have" (already STAGED — the producer can skip
        the page entirely), or "full" (over budget, or an in-flight
        reservation that may never complete — the producer must keep its
        TCP fallback)."""
        with self._lock:
            self._sweep_locked()
            if key in self._pages:
                return "have"  # staged and ready for admission
            if key in self._reserved:
                # an in-flight reservation may never complete (producer died
                # mid-handshake); do NOT claim we have it — the producer must
                # keep its TCP fallback for this page
                return "full"
            if self._bytes + nbytes > self.max_bytes:
                return "full"
            self._reserved[key] = (nbytes, self._time() + self.ttl)
            self._bytes += nbytes
            return "reserved"

    def unreserve(self, key: str) -> None:
        with self._lock:
            res = self._reserved.pop(key, None)
            if res is not None:
                self._bytes -= res[0]

    def put(self, key: str, k_dev, v_dev) -> None:
        """Convert a reservation into a staged page (sizes may differ from
        the reserved estimate; the delta is accounted)."""
        with self._lock:
            res = self._reserved.pop(key, None)
            if res is not None:
                self._bytes -= res[0]
            if key not in self._pages:
                self._pages[key] = (k_dev, v_dev, self._time() + self.ttl)
                self._bytes += int(k_dev.nbytes) * 2

    def contains(self, key: str) -> bool:
        with self._lock:
            self._sweep_locked()
            return key in self._pages

    def pop(self, key: str):
        with self._lock:
            entry = self._pages.pop(key, None)
            if entry is None:
                return None
            k_dev, v_dev, _ = entry
            self._bytes -= int(k_dev.nbytes) * 2
            return (k_dev, v_dev)

    def clear(self) -> None:
        with self._lock:
            self._pages.clear()
            self._reserved.clear()
            self._bytes = 0
