"""Disaggregated-prefill KV transfer: prefill (producer) -> decode (consumer).

TPU-native replacement for the reference's NIXL/UCX sender/receiver pair
(/root/reference helm/templates/deployment-vllm-multi.yaml:256-296:
`LMCACHE_ENABLE_NIXL`, `LMCACHE_NIXL_ROLE=sender/receiver`, receiver port
55555; examples/disaggregated_prefill/pd.yaml:22-65). No GPU-direct fabric on
TPU pods — KV pages ship as serde blobs over TCP (DCN between pods; loopback
within one) keyed by the same rolling chunk hashes the prefix cache uses, so
the decode engine's ordinary offload-restore path injects them into HBM.

Flow (two engines + router request_service.route_disaggregated_prefill_request):
1. Router sends the prompt to the prefill engine with max_tokens=1.
2. Producer engine, at sequence finish and *before* answering the prefill
   HTTP request, pushes each full page's blob to the consumer's receiver —
   so the KV is already there when the router's phase-2 decode request lands.
3. Consumer's receiver drops blobs into its offload store; decode admission
   restores them via KVPageManager.match_prefix (offload extension path).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from production_stack_tpu.kvoffload.protocol import (
    BlockingClient,
    parse_hostport,
    read_frame,
    write_frame,
)
from production_stack_tpu.kvoffload.serde import KVIntegrityError, verify_blob
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class KVTransferReceiver:
    """TCP server inside the decode (consumer) engine process; pushes land in
    the engine's tiered store where prefix-match admission finds them."""

    def __init__(
        self,
        store,
        host: str = "0.0.0.0",
        port: int = 55555,
        device_endpoint=None,
        staging=None,
    ):
        self.store = store
        self.host, self.port = host, port
        # device-to-device mode (DeviceKVEndpoint + DeviceStaging): producers
        # announce pages via "page_ready" and we pull them device->device
        self.device_endpoint = device_endpoint
        self.staging = staging
        # multi-host consumer mode (engine.enable_multihost_device_kv): the
        # number of mesh processes (page_query advertises it so the producer
        # can build one pull assignment per process) and a pull_fn/unstage_fn
        # pair that run the REPLICATED kv_pull_page / kv_unstage_page
        # dispatches on the engine device thread
        self.procs = 1
        self.pull_fn = None
        self.unstage_fn = None
        self.received_chunks = 0
        self.received_bytes = 0
        self.device_pages = 0
        # pushes rejected by the integrity check (bit-flipped in flight or a
        # producer on an incompatible serde format) — never enter the store
        self.corrupt_chunks = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.bound_port: Optional[int] = None

    async def _handle(self, reader, writer):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = hdr.get("op")
                if op == "push":
                    try:
                        verify_blob(payload)
                    except KVIntegrityError as e:
                        # refuse the page: a corrupt blob admitted here would
                        # later scatter wrong KV into the decode pools. The
                        # producer keeps its copy; admission falls back to
                        # the TCP-retry / recompute path.
                        self.corrupt_chunks += 1
                        logger.warning(
                            "rejecting corrupt kv push %s from %s: %s",
                            hdr.get("key"), peer, e,
                        )
                        await write_frame(writer, {"ok": False, "error": "integrity"})
                        continue
                    self.store.put_local(hdr["key"], payload)
                    self.received_chunks += 1
                    self.received_bytes += len(payload)
                    await write_frame(writer, {"ok": True})
                elif op == "page_query":
                    # device path phase 1: atomically reserve staging budget
                    # so the producer registers the page with its transfer
                    # server only once a pull is guaranteed to be attempted
                    device_on = self.pull_fn is not None or (
                        self.device_endpoint is not None
                    )
                    if not device_on or self.staging is None:
                        await write_frame(writer, {"ok": False})
                    else:
                        verdict = self.staging.reserve(
                            hdr["key"], int(hdr["nbytes"])
                        )
                        await write_frame(writer, {
                            "ok": verdict == "reserved",
                            "have": verdict == "have",
                            "procs": self.procs,
                        })
                elif op == "page_ready" and "assignments" in hdr:
                    # device path phase 2, assignment form (producer armed
                    # via enable_multihost — also the P=1 single-host case):
                    # a multi-host consumer pulls one copy per process
                    # (REPLICATED kv_pull_page via the engine device
                    # thread); a single-host consumer pulls assignment 0
                    # with its own endpoint
                    ok = False
                    key = hdr["key"]
                    if self.pull_fn is not None and self.staging is not None:
                        nbytes = 0
                        try:
                            nbytes = int(await asyncio.to_thread(
                                self.pull_fn,
                                hdr["assignments"], hdr["shape"],
                                hdr["dtype"], key,
                            ) or 0)
                        except Exception as e:  # noqa: BLE001
                            logger.warning(
                                "multi-host device kv pull failed: %s", e
                            )
                        ok = nbytes > 0
                        if ok:
                            self.staging.promote(key, nbytes)
                            self.device_pages += 1
                        else:
                            self.staging.unreserve(key)
                            if self.unstage_fn is not None:
                                # a partial pull may have staged copies on
                                # some processes; converge everyone to empty
                                await asyncio.to_thread(self.unstage_fn, key)
                    elif self.device_endpoint is not None and self.staging is not None:
                        addr, uuid = hdr["assignments"][0]
                        try:
                            # pull probes the producer address on every
                            # call AND materializes the arrays inside this
                            # timed thread: the XLA transfer pull is lazy
                            # and would otherwise "succeed" against a dead
                            # producer (hanging only on first use,
                            # uninterruptibly) — the TCP blob fallback
                            # contract needs the failure HERE, before
                            # staging.put publishes the page
                            k_dev, v_dev = await asyncio.wait_for(
                                asyncio.to_thread(
                                    self.device_endpoint.pull,
                                    addr, int(uuid), hdr["shape"],
                                    hdr["dtype"],
                                ),
                                timeout=15.0,
                            )
                            self.staging.put(key, k_dev, v_dev)
                            self.device_pages += 1
                            ok = True
                        except (Exception, asyncio.TimeoutError) as e:  # noqa: BLE001
                            self.staging.unreserve(key)
                            if self.device_endpoint is not None:
                                self.device_endpoint.mark_dead(addr)
                            logger.warning("device kv pull failed: %s", e)
                    await write_frame(writer, {"ok": ok})
                elif op == "ping":
                    await write_frame(writer, {"ok": True})
                else:
                    await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})
        except Exception as e:
            logger.warning("kv receiver: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def start(self) -> None:
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def serve():
                server = await asyncio.start_server(self._handle, self.host, self.port)
                self.bound_port = server.sockets[0].getsockname()[1]
                self._started.set()
                async with server:
                    await server.serve_forever()

            try:
                self._loop.run_until_complete(serve())
            except asyncio.CancelledError:
                pass

        self._thread = threading.Thread(target=run, daemon=True, name="kv-receiver")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("KV transfer receiver failed to start")
        logger.info("kv transfer receiver on %s:%s", self.host, self.bound_port)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        if self._thread is not None:
            self._thread.join(timeout=5)


class KVTransferSender:
    """Producer-side pusher. Called on the engine device thread at sequence
    finish — synchronous by design: the prefill HTTP response must not return
    before the decode peer holds the KV (the reference gets the same ordering
    from the NIXL blocking handshake)."""

    def __init__(self, peer_url: str, timeout: float = 30.0):
        host, port = parse_hostport(peer_url, default_port=55555)
        self._client = BlockingClient(host, port, timeout=timeout)
        self._lock = threading.Lock()
        # device path (engine-armed, single- OR multi-host producer):
        # per-process transfer-server addresses and the REPLICATED offer
        # dispatch (runner.kv_offer_page via the broadcasting runner)
        self._mh_addrs: Optional[list] = None
        self._mh_offer = None
        self._mh_uuid = 1 << 20  # clear of any endpoint-self-assigned ids
        self.sent_chunks = 0
        self.sent_bytes = 0
        self.device_pages = 0
        self.skipped_pages = 0
        self.errors = 0

    def enable_multihost(self, addrs: list, offer_fn) -> None:
        """Arm the multi-host device path: ``addrs`` lists every producer
        process's transfer-server address (index == jax process id);
        ``offer_fn(pid, uuid_base, pullers) -> (shape, dtype)`` performs the
        replicated page offer on every producer process."""
        self._mh_addrs = addrs
        self._mh_offer = offer_fn

    def push_device_multihost(self, key: str, nbytes: int, pid: int) -> bool:
        """Multi-host NIXL analogue: one page moves shard-cluster to
        shard-cluster with no host serde. Phase 1 reserves consumer staging
        (and learns the consumer process count C); phase 2 offers the
        replicated page on every producer process (P of them) and announces
        one (addr, uuid) pull assignment per consumer process — consumer c
        pulls from producer c % P under uuid = base + (c // P) * P + (c % P).
        Returns False for per-page TCP-blob fallback."""
        if self._mh_addrs is None or self._mh_offer is None:
            return False
        try:
            with self._lock:
                hdr, _ = self._client.request(
                    {"op": "page_query", "key": key, "nbytes": nbytes}
                )
                if hdr.get("have"):
                    self.skipped_pages += 1
                    return True
                if not hdr.get("ok"):
                    return False
                procs = max(1, int(hdr.get("procs", 1)))
                n_prod = len(self._mh_addrs)
                base = self._mh_uuid
                self._mh_uuid += procs  # disjoint uuid range per page
                shape, dtype = self._mh_offer(pid, base, procs)
                # consumer c pulls from producer c % P under uuid base + c;
                # producer process i offered exactly {base+c : c % P == i}
                assignments = [
                    [self._mh_addrs[c % n_prod], base + c]
                    for c in range(procs)
                ]
                hdr, _ = self._client.request({
                    "op": "page_ready", "key": key,
                    "assignments": assignments,
                    "shape": shape, "dtype": dtype,
                })
            if bool(hdr.get("ok")):
                self.device_pages += 1
                return True
            return False
        except Exception as e:  # noqa: BLE001
            self.errors += 1
            logger.warning("multi-host device kv offer failed: %s", e)
            return False

    def push(self, key: str, blob: bytes) -> bool:
        with self._lock:
            try:
                hdr, _ = self._client.request({"op": "push", "key": key}, blob)
                if hdr.get("ok"):
                    self.sent_chunks += 1
                    self.sent_bytes += len(blob)
                    return True
                return False
            except Exception as e:
                self.errors += 1
                logger.warning("kv transfer push failed: %s", e)
                return False

    def close(self) -> None:
        self._client.close()


# -- device-to-device path (co-located prefill/decode slices) -----------------


class DeviceKVEndpoint:
    """One engine's side of the jax device-to-device KV fabric.

    Wraps ``jax.experimental.transfer``: producer processes register page
    arrays for pull under leader-assigned uuids (``offer_fixed``, driven by
    the replicated runner.kv_offer_page); consumer processes pull them
    straight into their own devices (``pull``) — KV moves device->device
    over the XLA transfer service (ICI within a slice, DCN between pods)
    with no host serde round trip. This is the stack's NIXL-GPU-direct
    analogue (reference deployment-vllm-multi.yaml:256-296); the TCP blob
    path remains the per-page fallback.
    """

    def __init__(self, runner, host: str = "127.0.0.1"):
        import jax
        from jax.experimental import transfer

        self.runner = runner
        # the PROCESS-LOCAL device: on a multi-host mesh, mesh.devices
        # includes non-addressable devices whose client cannot host this
        # process's transfer server
        self._local_dev = next(
            d for d in runner.mesh.devices.flat
            if d.process_index == jax.process_index()
        )
        self._server = transfer.start_transfer_server(
            self._local_dev.client, f"{host}:0", [f"{host}:0"]
        )
        self.address = self._server.address()
        self._conns: dict = {}
        self._offered: dict[int, tuple] = {}  # uuid -> arrays (kept alive)
        self._uuid = 0
        self._lock = threading.Lock()
        self.offered_pages = 0
        self.pulled_pages = 0
        # leak accounting: offers retired by TTL (the producer cannot tell a
        # pulled offer from an abandoned one — no release handshake — so this
        # is an UPPER BOUND on leaks; XLA's await_pull has no cancel, so an
        # unpulled registration's device buffers outlive the dropped Python
        # ref). Cap-evictions are counted separately: they indicate budget
        # pressure, not age.
        self.leaked_offers = 0
        self.cap_evicted_offers = 0
        self._dead_addrs: dict[str, float] = {}    # addr -> retry-after

    # Retirement policy for fixed offers: there is no per-offer release
    # handshake (the consumer's ack proves only its LEADER pulled; its
    # followers replay the pull from the step stream asynchronously), so the
    # producer cannot safely drop a ref on ack. Instead refs retire by AGE
    # (past any plausible in-flight pull — consumer-side staging gives up at
    # 120 s) with a hard count cap as backstop; a pulled offer's buffers
    # free with the ref, and an unpulled one that old has already failed on
    # the consumer (unstage + the producer's TCP fallback). sweep() runs on
    # every new offer (on every process — offers are replicated), so an
    # idle producer pins at most its final ~120 s of transferred pages.
    OFFER_TTL = 120.0
    OFFER_CAP = 256
    # size-aware budget: the count cap alone lets 256 fully-replicated pages
    # pin GBs of HBM at realistic page sizes under sustained transfer; the
    # byte cap retires oldest offers first once the pinned set crosses it
    OFFER_BYTES_CAP = 256 << 20

    @staticmethod
    def _offer_bytes(entry: tuple) -> int:
        k, v = entry[0], entry[1]
        return int(getattr(k, "nbytes", 0)) + int(getattr(v, "nbytes", 0))

    def pinned_offer_bytes(self) -> int:
        """HBM currently pinned by live offers (per local device replica)."""
        with self._lock:
            return sum(self._offer_bytes(e) for e in self._offered.values())

    def sweep(self) -> None:
        import time as time_mod

        now = time_mod.monotonic()
        with self._lock:
            for u in [u for u, (_, _, d) in self._offered.items() if d < now]:
                self._offered.pop(u)
                self.leaked_offers += 1
            pinned = sum(self._offer_bytes(e) for e in self._offered.values())
            while self._offered and (
                len(self._offered) > self.OFFER_CAP
                or pinned > self.OFFER_BYTES_CAP
            ):
                entry = self._offered.pop(next(iter(self._offered)))  # oldest
                pinned -= self._offer_bytes(entry)
                self.cap_evicted_offers += 1

    def offer_fixed(self, uuid: int, k_dev, v_dev) -> None:
        """Offer under a caller-chosen uuid (multi-host: the leader assigns
        uuids and replicates the offer so every process registers its local
        copy under a predictable id; see runner.kv_offer_page)."""
        import time as time_mod

        self.sweep()
        with self._lock:
            self._offered[uuid] = (
                k_dev, v_dev, time_mod.monotonic() + self.OFFER_TTL
            )
            # keep self-assigned uuids clear of leader-assigned ranges
            self._uuid = max(self._uuid, uuid + 1)
        self._server.await_pull(uuid, [k_dev, v_dev])
        self.offered_pages += 1

    DEAD_ADDR_TTL = 60.0

    def mark_dead(self, addr: str) -> None:
        """Negative-cache a producer address after a failed/hung pull so
        subsequent pages fail fast to the TCP blob path instead of each
        eating a pull timeout (and leaking a blocked thread)."""
        import time as time_mod

        with self._lock:
            self._dead_addrs[addr] = time_mod.monotonic() + self.DEAD_ADDR_TTL
            self._conns.pop(addr, None)

    def _probe_addr(self, addr: str) -> None:
        """Fail fast on an unreachable producer. The XLA transfer pull is
        LAZY: connect()+pull() against a dead address "succeed" and the
        returned arrays only hang when first consumed. A plain TCP probe
        catches the realistic failure (producer pod gone) before any page is
        staged. Probes run on EVERY pull — a local connect is ~ms against a
        page transfer's tens of ms, and a cached probe verdict (the old
        30 s TTL) let a producer that died after its probe hand back lazy
        arrays that only hung once a consumer touched them."""
        import socket

        host, _, port = addr.rpartition(":")
        try:
            socket.create_connection((host or "127.0.0.1", int(port)),
                                     timeout=3.0).close()
        except OSError as e:
            raise ConnectionError(f"kv producer {addr} unreachable: {e}") from e

    def pull(self, addr: str, uuid: int, shape, dtype):
        """Pull a page's (k, v) device arrays from the producer at ``addr``
        and MATERIALIZE them before returning: reachability is probed first
        (see _probe_addr) and the block_until_ready runs inside whatever
        timed thread the caller wrapped around this call, so a producer that
        dies mid-transfer is caught here — before staging.put publishes a
        page that would hang its first consumer (that hang is not
        interruptible; the caller's timeout leaks this worker thread, the
        lesser evil against a wedged engine loop)."""
        import time as time_mod

        import jax
        import jax.numpy as jnp

        with self._lock:
            dead_until = self._dead_addrs.get(addr, 0.0)
            if dead_until > time_mod.monotonic():
                raise ConnectionError(
                    f"kv producer {addr} marked dead until {dead_until:.0f}"
                )
            self._dead_addrs.pop(addr, None)
        self._probe_addr(addr)
        with self._lock:
            conn = self._conns.get(addr)
            if conn is None:
                conn = self._server.connect(addr)
                self._conns[addr] = conn
        dev = self._local_dev
        sds = jax.ShapeDtypeStruct(
            tuple(shape), jnp.dtype(dtype),
            sharding=jax.sharding.SingleDeviceSharding(dev),
        )
        k_dev, v_dev = conn.pull(uuid, [sds, sds])
        jax.block_until_ready((k_dev, v_dev))
        self.pulled_pages += 1
        return k_dev, v_dev

    def close(self) -> None:
        """Drop connections and any still-offered arrays. The XLA API has no
        transfer-server shutdown; releasing the Python references lets the
        server object (and its device buffers) be collected with us."""
        with self._lock:
            self._conns.clear()
            self._offered.clear()
        self._server = None


class DeviceStaging:
    """Consumer-side staging for device-pulled pages awaiting admission.

    Pulled pages live on device until the decode request's prefix match
    injects them into the pool (runner.set_page — a device->device copy).
    Bounded and self-cleaning: budget is reserved atomically BEFORE the pull
    (so concurrent producers cannot overcommit), and both reservations and
    staged pages expire after ``ttl`` seconds — a decode request that never
    arrives (client abort after prefill) must not pin consumer HBM or wedge
    the budget into permanent TCP fallback."""

    _META = "META"  # sentinel k-slot: page staged per-process in runner.kv_staged

    def __init__(self, max_bytes: int = 1 << 30, ttl: float = 120.0,
                 on_expire=None):
        import time as time_mod

        self._time = time_mod.monotonic
        self.max_bytes = max_bytes
        self.ttl = ttl
        # on_expire(key): fired (outside the lock) when a META entry expires —
        # multi-host consumers replicate kv_unstage_page so every process
        # drops its staged copy together with this accounting entry
        self.on_expire = on_expire
        self._pages: dict[str, tuple] = {}      # key -> (k|META, v|nbytes, deadline)
        self._reserved: dict[str, tuple] = {}   # key -> (nbytes, deadline)
        self._bytes = 0
        self._lock = threading.Lock()
        self._expire_q = None  # lazy single-worker on_expire queue
        self.expired_pages = 0

    @classmethod
    def _entry_bytes(cls, entry: tuple) -> int:
        k, v, _ = entry
        return int(v) if isinstance(k, str) else int(k.nbytes) * 2

    def _sweep_locked(self) -> list:
        """Drop expired entries; returns expired META keys so the caller can
        fire ``on_expire`` after releasing the lock."""
        now = self._time()
        expired_meta = []
        for key in [k for k, (_, _, d) in self._pages.items() if d < now]:
            entry = self._pages.pop(key)
            self._bytes -= self._entry_bytes(entry)
            self.expired_pages += 1
            if isinstance(entry[0], str):
                expired_meta.append(key)
        for key in [k for k, (_, d) in self._reserved.items() if d < now]:
            nbytes, _ = self._reserved.pop(key)
            self._bytes -= nbytes
        return expired_meta

    def _fire_expired(self, keys: list) -> None:
        """Queue on_expire for a single BACKGROUND worker. reserve()/
        contains() run on the KV receiver's asyncio event loop (page_query
        handler), and on_expire -> engine unstage blocks on the engine
        device thread — up to ~2 min mid-deep-chain. Firing inline would
        head-of-line-block every KV transfer connection behind one expiry;
        a thread PER sweep would pile up unboundedly behind a wedged device
        thread, so one worker drains a queue. The worker re-checks each key
        under the lock right before firing: a page re-staged (or
        re-reserved) while the callback sat queued must NOT have its fresh
        copy dropped by a stale expiry."""
        if self.on_expire is None or not keys:
            return
        with self._lock:
            if self._expire_q is None:
                import queue as queue_mod

                self._expire_q = queue_mod.Queue()
                threading.Thread(
                    target=self._expire_worker, daemon=True,
                    name="kv-staging-expire",
                ).start()
        for k in keys:
            self._expire_q.put(k)

    def _expire_worker(self) -> None:
        while True:
            k = self._expire_q.get()
            with self._lock:
                if k in self._pages or k in self._reserved:
                    continue  # re-staged while the callback was queued
            try:
                self.on_expire(k)
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                logger.exception("staging on_expire(%s) failed", k)

    def reserve(self, key: str, nbytes: int) -> str:
        """Atomically check-and-reserve budget for an incoming page.
        Returns "reserved", "have" (already STAGED — the producer can skip
        the page entirely), or "full" (over budget, or an in-flight
        reservation that may never complete — the producer must keep its
        TCP fallback)."""
        with self._lock:
            expired = self._sweep_locked()
            if key in self._pages:
                verdict = "have"  # staged and ready for admission
            elif key in self._reserved:
                # an in-flight reservation may never complete (producer died
                # mid-handshake); do NOT claim we have it — the producer must
                # keep its TCP fallback for this page
                verdict = "full"
            elif self._bytes + nbytes > self.max_bytes:
                verdict = "full"
            else:
                self._reserved[key] = (nbytes, self._time() + self.ttl)
                self._bytes += nbytes
                verdict = "reserved"
        self._fire_expired(expired)
        return verdict

    def promote(self, key: str, nbytes: int = 0) -> None:
        """Convert a reservation into a META entry: the page's device copies
        live per process in runner.kv_staged (multi-host pull); this object
        keeps only the budget accounting and admission visibility. ``nbytes``
        is the pulled page's real size — charged when the reservation TTL'd
        out during a slow pull, so staged HBM never escapes the budget."""
        with self._lock:
            res = self._reserved.pop(key, None)
            if res is not None:
                # reservation bytes stay counted; they simply become the
                # page's accounting entry
                size = res[0]
            else:
                size = nbytes
                self._bytes += size
            if key not in self._pages:
                self._pages[key] = (self._META, size, self._time() + self.ttl)
            else:
                self._bytes -= size  # already staged; drop the double count

    def unreserve(self, key: str) -> None:
        with self._lock:
            res = self._reserved.pop(key, None)
            if res is not None:
                self._bytes -= res[0]

    def put(self, key: str, k_dev, v_dev) -> None:
        """Convert a reservation into a staged page (sizes may differ from
        the reserved estimate; the delta is accounted)."""
        with self._lock:
            res = self._reserved.pop(key, None)
            if res is not None:
                self._bytes -= res[0]
            if key not in self._pages:
                self._pages[key] = (k_dev, v_dev, self._time() + self.ttl)
                self._bytes += int(k_dev.nbytes) * 2

    def contains(self, key: str) -> bool:
        with self._lock:
            expired = self._sweep_locked()
            found = key in self._pages
        self._fire_expired(expired)
        return found

    def pop(self, key: str):
        """Staged arrays, the string "replicated" for a multi-host META entry
        (restore via runner.kv_restore_page), or None."""
        with self._lock:
            entry = self._pages.pop(key, None)
            if entry is None:
                return None
            self._bytes -= self._entry_bytes(entry)
            if isinstance(entry[0], str):
                return "replicated"
            k_dev, v_dev, _ = entry
            return (k_dev, v_dev)

    def clear(self) -> None:
        with self._lock:
            self._pages.clear()
            self._reserved.clear()
            self._bytes = 0
