"""Disaggregated-prefill KV transfer: prefill (producer) -> decode (consumer).

TPU-native replacement for the reference's NIXL/UCX sender/receiver pair
(/root/reference helm/templates/deployment-vllm-multi.yaml:256-296:
`LMCACHE_ENABLE_NIXL`, `LMCACHE_NIXL_ROLE=sender/receiver`, receiver port
55555; examples/disaggregated_prefill/pd.yaml:22-65). No GPU-direct fabric on
TPU pods — KV pages ship as serde blobs over TCP (DCN between pods; loopback
within one) keyed by the same rolling chunk hashes the prefix cache uses, so
the decode engine's ordinary offload-restore path injects them into HBM.

Flow (two engines + router request_service.route_disaggregated_prefill_request):
1. Router sends the prompt to the prefill engine with max_tokens=1.
2. Producer engine, at sequence finish and *before* answering the prefill
   HTTP request, pushes each full page's blob to the consumer's receiver —
   so the KV is already there when the router's phase-2 decode request lands.
3. Consumer's receiver drops blobs into its offload store; decode admission
   restores them via KVPageManager.match_prefix (offload extension path).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from production_stack_tpu.kvoffload.protocol import (
    BlockingClient,
    parse_hostport,
    read_frame,
    write_frame,
)
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class KVTransferReceiver:
    """TCP server inside the decode (consumer) engine process; pushes land in
    the engine's tiered store where prefix-match admission finds them."""

    def __init__(self, store, host: str = "0.0.0.0", port: int = 55555):
        self.store = store
        self.host, self.port = host, port
        self.received_chunks = 0
        self.received_bytes = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.bound_port: Optional[int] = None

    async def _handle(self, reader, writer):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = hdr.get("op")
                if op == "push":
                    self.store.put_local(hdr["key"], payload)
                    self.received_chunks += 1
                    self.received_bytes += len(payload)
                    await write_frame(writer, {"ok": True})
                elif op == "ping":
                    await write_frame(writer, {"ok": True})
                else:
                    await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})
        except Exception as e:
            logger.warning("kv receiver: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def start(self) -> None:
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def serve():
                server = await asyncio.start_server(self._handle, self.host, self.port)
                self.bound_port = server.sockets[0].getsockname()[1]
                self._started.set()
                async with server:
                    await server.serve_forever()

            try:
                self._loop.run_until_complete(serve())
            except asyncio.CancelledError:
                pass

        self._thread = threading.Thread(target=run, daemon=True, name="kv-receiver")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("KV transfer receiver failed to start")
        logger.info("kv transfer receiver on %s:%s", self.host, self.bound_port)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        if self._thread is not None:
            self._thread.join(timeout=5)


class KVTransferSender:
    """Producer-side pusher. Called on the engine device thread at sequence
    finish — synchronous by design: the prefill HTTP response must not return
    before the decode peer holds the KV (the reference gets the same ordering
    from the NIXL blocking handshake)."""

    def __init__(self, peer_url: str, timeout: float = 30.0):
        host, port = parse_hostport(peer_url, default_port=55555)
        self._client = BlockingClient(host, port, timeout=timeout)
        self._lock = threading.Lock()
        self.sent_chunks = 0
        self.sent_bytes = 0
        self.errors = 0

    def push(self, key: str, blob: bytes) -> bool:
        with self._lock:
            try:
                hdr, _ = self._client.request({"op": "push", "key": key}, blob)
                if hdr.get("ok"):
                    self.sent_chunks += 1
                    self.sent_bytes += len(blob)
                    return True
                return False
            except Exception as e:
                self.errors += 1
                logger.warning("kv transfer push failed: %s", e)
                return False

    def close(self) -> None:
        self._client.close()
