"""Standalone remote KV cache server — shared DRAM tier across engine pods.

TPU-native replacement for the reference's `lmcache_experimental_server`
deployment (/root/reference helm/templates/deployment-cache-server.yaml:33-74;
engines point at it via `LMCACHE_REMOTE_URL`,
deployment-vllm-multi.yaml:309-314). Speaks the frame protocol in
kvoffload/protocol.py; blobs are opaque serde bytes, so one server serves
engines using any serde.

Since ISSUE 9 the server also hosts the **fleet-wide KV directory**
(production_stack_tpu/kvdirectory, docs/kv-directory.md): engines publish
which chunk hashes they hold (and which blobs they spilled into this
server), the router consults it for KV-aware routing v2, and cold engines
pull fleet-warm prefixes through the ordinary get path. The directory rides
the same frame connection (``dir_*`` ops), is kept consistent with the blob
map (an evicted or quarantined blob immediately stops being advertised as
restorable), and persists snapshots to ``--directory-persist-path`` so a
server restart does not forget the fleet's claims. ``--metrics-port``
exposes the ``vllm:kv_directory_*`` surface for Prometheus.

Run: ``python -m production_stack_tpu.kvoffload.cache_server --port 8200``.
"""

from __future__ import annotations

import argparse
import asyncio
import os
from collections import OrderedDict
from typing import Optional

from production_stack_tpu.kvoffload.protocol import read_frame, write_frame
from production_stack_tpu.kvoffload.serde import (
    KVIntegrityError,
    seal_bytes,
    unseal_bytes,
    verify_blob,
)
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class CacheServer:
    def __init__(self, max_bytes: int = 4 << 30, directory=None):
        self.max_bytes = max_bytes
        self._data: OrderedDict[str, bytes] = OrderedDict()  # owned-by: event-loop
        self.used_bytes = 0
        self.gets = 0
        self.hits = 0
        self.puts = 0
        # entries that failed their integrity check on read and were dropped
        # (a shared server must never fan corruption out to the whole fleet)
        self.corrupt = 0
        # fleet-wide KV directory (kvdirectory.KVDirectory) — optional so the
        # plain blob-tier deployment shape keeps working unchanged
        self.directory = directory
        if directory is not None and directory.blob_check is None:
            # restorable lookups answer against the ACTUAL blob map, so a
            # capacity-evicted blob stops being advertised instantly
            directory.blob_check = self._contains

    def _contains(self, key: str) -> bool:
        return key in self._data

    # -- storage --------------------------------------------------------------

    def put(self, key: str, blob: bytes) -> None:
        self.puts += 1
        old = self._data.pop(key, None)
        if old is not None:
            self.used_bytes -= len(old)
        self._data[key] = blob
        self.used_bytes += len(blob)
        while self.used_bytes > self.max_bytes and self._data:
            k, b = self._data.popitem(last=False)
            self.used_bytes -= len(b)
            if self.directory is not None:
                self.directory.blob_evicted(k)

    def get(self, key: str) -> Optional[bytes]:
        self.gets += 1
        blob = self._data.get(key)
        if blob is None:
            return None
        try:
            verify_blob(blob)
        except KVIntegrityError as e:
            # quarantine: a corrupt entry on a SHARED server would otherwise
            # be re-fetched by every engine in the fleet; drop it and report
            # a miss so the caller falls back to another tier or recompute
            self.corrupt += 1
            self._data.pop(key, None)
            self.used_bytes -= len(blob)
            if self.directory is not None:
                self.directory.blob_evicted(key)
            logger.warning("cache server: quarantined corrupt blob %s: %s", key, e)
            return None
        self.hits += 1
        self._data.move_to_end(key)
        return blob

    def stats(self) -> dict:
        out = {
            "entries": len(self._data),
            "used_bytes": self.used_bytes,
            "max_bytes": self.max_bytes,
            "gets": self.gets,
            "hits": self.hits,
            "puts": self.puts,
            "corrupt": self.corrupt,
        }
        if self.directory is not None:
            out.update(self.directory.stats())
        return out

    # -- directory persistence -------------------------------------------------

    def directory_snapshot_blob(self) -> Optional[bytes]:
        """Serialize the directory ON the event loop: the index is
        single-writer on this loop (kvdirectory/directory.py), so a worker
        thread would iterate dicts the loop concurrently mutates and die
        with 'dictionary changed size during iteration' on every busy
        interval. Only the file WRITE belongs off-loop."""
        if self.directory is None:
            return None
        return seal_bytes(self.directory.snapshot_json(), kind="kvdirectory")

    @staticmethod
    def write_snapshot(path: str, blob: bytes) -> None:
        """Atomic-replace file write (runs in asyncio.to_thread)."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    def load_directory_snapshot(self, path: str) -> int:
        if self.directory is None or not os.path.exists(path):
            return 0
        import json

        try:
            with open(path, "rb") as f:
                _, body = unseal_bytes(f.read())
            return self.directory.load_snapshot(json.loads(body))
        except (OSError, ValueError, KVIntegrityError) as e:
            # a rotted snapshot is a cold directory, not a boot failure —
            # engines republish on their flush cadence anyway
            logger.warning("cache server: unreadable directory snapshot: %s", e)
            return 0

    # -- protocol -------------------------------------------------------------

    async def handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = hdr.get("op")
                if op == "put":
                    self.put(hdr["key"], payload)
                    await write_frame(writer, {"ok": True})
                elif op == "get":
                    blob = self.get(hdr["key"])
                    await write_frame(
                        writer, {"ok": True, "found": blob is not None}, blob or b""
                    )
                elif op == "exists":
                    await write_frame(
                        writer, {"ok": True, "found": hdr["key"] in self._data}
                    )
                elif op == "delete":
                    blob = self._data.pop(hdr["key"], None)
                    if blob is not None:
                        self.used_bytes -= len(blob)
                        if self.directory is not None:
                            self.directory.blob_evicted(hdr["key"])
                    await write_frame(writer, {"ok": True, "found": blob is not None})
                elif op == "stats":
                    await write_frame(writer, {"ok": True, **self.stats()})
                elif op == "ping":
                    await write_frame(writer, {"ok": True})
                elif isinstance(op, str) and op.startswith("dir_"):
                    await self._handle_dir(writer, op, hdr)
                else:
                    await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})
        except Exception as e:  # keep the server alive across bad clients
            logger.warning("cache server: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _handle_dir(self, writer, op: str, hdr: dict) -> None:
        d = self.directory
        if d is None:
            await write_frame(
                writer, {"ok": False, "error": "directory disabled"}
            )
            return
        if op == "dir_register":
            d.register(
                hdr["url"], int(hdr.get("page_size", 0)),
                int(hdr.get("generation", 0)),
            )
            await write_frame(writer, {"ok": True})
        elif op == "dir_publish":
            n = d.publish(
                hdr["url"], int(hdr.get("generation", 0)),
                hdr.get("entries", []), hdr.get("tier", "hbm"),
                page_size=int(hdr.get("page_size", 0)),
            )
            await write_frame(writer, {"ok": True, "published": n})
        elif op == "dir_withdraw":
            n = d.withdraw(
                hdr["url"], hdr.get("hashes", []),
                hdr.get("scope", "resident"),
            )
            await write_frame(writer, {"ok": True, "withdrawn": n})
        elif op == "dir_lookup":
            res = d.lookup_tokens(hdr.get("tokens", []), hdr.get("salt", ""))
            await write_frame(writer, {"ok": True, **res})
        elif op == "dir_lookup_hashes":
            res = d.lookup_hashes(hdr.get("hashes", []))
            await write_frame(writer, {"ok": True, **res})
        elif op == "dir_top_prefixes":
            hashes = d.top_prefixes(
                int(hdr.get("limit", 0)), int(hdr.get("page_size", 0))
            )
            await write_frame(writer, {"ok": True, "hashes": hashes})
        elif op == "dir_stats":
            await write_frame(writer, {"ok": True, **d.stats()})
        elif op == "dir_dump":
            await write_frame(writer, {"ok": True, **d.dump()})
        else:
            await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})

    def metrics_text(self) -> str:
        """Prometheus exposition for --metrics-port: the kv-directory surface
        (docs/kv-directory.md, check_metrics_coverage.py)."""
        if self.directory is None:
            return ""
        s = self.directory.stats()
        lines = []
        for name, kind in (
            ("vllm:kv_directory_entries", "gauge"),
            ("vllm:kv_directory_engines", "gauge"),
            ("vllm:kv_directory_publishes_total", "counter"),
            ("vllm:kv_directory_withdrawals_total", "counter"),
            ("vllm:kv_directory_stale_hits_total", "counter"),
            ("vllm:kv_directory_expired_entries_total", "counter"),
            ("vllm:kv_directory_lookups_total", "counter"),
        ):
            key = name.split(":", 1)[1]
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f'{name}{{server="cache"}} {s.get(key, 0)}')
        return "\n".join(lines) + "\n"


async def _persist_loop(cs: CacheServer, path: str, interval: float) -> None:
    """Periodic offload-tier-backed persistence of the directory index:
    serialize on the loop (single-writer safety), write off it."""
    while True:
        await asyncio.sleep(interval)
        try:
            blob = cs.directory_snapshot_blob()
            if blob is not None:
                await asyncio.to_thread(cs.write_snapshot, path, blob)
        except Exception:  # noqa: BLE001 - persistence is best-effort
            logger.exception("cache server: directory snapshot failed")


async def _serve_metrics(cs: CacheServer, host: str, port: int):
    """Tiny HTTP /metrics endpoint for Prometheus (aiohttp, like the other
    first-party servers)."""
    from aiohttp import web

    async def metrics(request):
        return web.Response(text=cs.metrics_text(), content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("cache server metrics on %s:%d", host, port)
    return runner


async def serve(
    host: str,
    port: int,
    max_bytes: int,
    *,
    directory: bool = True,
    directory_persist_path: Optional[str] = None,
    directory_persist_interval: float = 30.0,
    directory_engine_timeout: float = 60.0,
    metrics_port: int = 0,
) -> asyncio.AbstractServer:
    d = None
    if directory:
        from production_stack_tpu.kvdirectory import KVDirectory

        d = KVDirectory(engine_timeout=directory_engine_timeout)
    cs = CacheServer(max_bytes, directory=d)
    if d is not None and directory_persist_path:
        cs.load_directory_snapshot(directory_persist_path)
        # keep a strong reference on the server object: the event loop holds
        # only a weak ref to tasks, and a GC'd persist loop would silently
        # stop snapshots on a long-lived, mostly-idle server
        cs._persist_task = asyncio.get_running_loop().create_task(
            _persist_loop(cs, directory_persist_path, directory_persist_interval)
        )
    if metrics_port:
        await _serve_metrics(cs, host, metrics_port)
    server = await asyncio.start_server(cs.handle, host, port)
    logger.info(
        "kv cache server on %s:%d (%.1f GB, directory=%s)",
        host, port, max_bytes / 1e9, "on" if d is not None else "off",
    )
    return server


def main() -> None:
    p = argparse.ArgumentParser(description="TPU-stack remote KV cache server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--max-bytes", type=int, default=4 << 30)
    p.add_argument("--directory", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="host the fleet-wide KV directory (dir_* ops; "
                        "docs/kv-directory.md); --no-directory disables")
    p.add_argument("--directory-persist-path", type=str, default=None,
                   help="file the directory index snapshots to (sealed JSON, "
                        "atomic replace) and reloads from at boot; unset = "
                        "in-memory only")
    p.add_argument("--directory-persist-interval", type=float, default=30.0,
                   help="seconds between directory snapshots")
    p.add_argument("--directory-engine-timeout", type=float, default=60.0,
                   help="seconds of engine silence before its resident "
                        "claims expire from the directory")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve GET /metrics (vllm:kv_directory_*) on this "
                        "port; 0 disables")
    args = p.parse_args()

    async def run():
        server = await serve(
            args.host, args.port, args.max_bytes,
            directory=args.directory,
            directory_persist_path=args.directory_persist_path,
            directory_persist_interval=args.directory_persist_interval,
            directory_engine_timeout=args.directory_engine_timeout,
            metrics_port=args.metrics_port,
        )
        async with server:
            await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
