"""Standalone remote KV cache server — shared DRAM tier across engine pods.

TPU-native replacement for the reference's `lmcache_experimental_server`
deployment (/root/reference helm/templates/deployment-cache-server.yaml:33-74;
engines point at it via `LMCACHE_REMOTE_URL`,
deployment-vllm-multi.yaml:309-314). Speaks the frame protocol in
kvoffload/protocol.py; blobs are opaque serde bytes, so one server serves
engines using any serde.

Run: ``python -m production_stack_tpu.kvoffload.cache_server --port 8200``.
"""

from __future__ import annotations

import argparse
import asyncio
from collections import OrderedDict
from typing import Optional

from production_stack_tpu.kvoffload.protocol import read_frame, write_frame
from production_stack_tpu.kvoffload.serde import KVIntegrityError, verify_blob
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class CacheServer:
    def __init__(self, max_bytes: int = 4 << 30):
        self.max_bytes = max_bytes
        self._data: OrderedDict[str, bytes] = OrderedDict()
        self.used_bytes = 0
        self.gets = 0
        self.hits = 0
        self.puts = 0
        # entries that failed their integrity check on read and were dropped
        # (a shared server must never fan corruption out to the whole fleet)
        self.corrupt = 0

    # -- storage --------------------------------------------------------------

    def put(self, key: str, blob: bytes) -> None:
        self.puts += 1
        old = self._data.pop(key, None)
        if old is not None:
            self.used_bytes -= len(old)
        self._data[key] = blob
        self.used_bytes += len(blob)
        while self.used_bytes > self.max_bytes and self._data:
            _, b = self._data.popitem(last=False)
            self.used_bytes -= len(b)

    def get(self, key: str) -> Optional[bytes]:
        self.gets += 1
        blob = self._data.get(key)
        if blob is None:
            return None
        try:
            verify_blob(blob)
        except KVIntegrityError as e:
            # quarantine: a corrupt entry on a SHARED server would otherwise
            # be re-fetched by every engine in the fleet; drop it and report
            # a miss so the caller falls back to another tier or recompute
            self.corrupt += 1
            self._data.pop(key, None)
            self.used_bytes -= len(blob)
            logger.warning("cache server: quarantined corrupt blob %s: %s", key, e)
            return None
        self.hits += 1
        self._data.move_to_end(key)
        return blob

    def stats(self) -> dict:
        return {
            "entries": len(self._data),
            "used_bytes": self.used_bytes,
            "max_bytes": self.max_bytes,
            "gets": self.gets,
            "hits": self.hits,
            "puts": self.puts,
            "corrupt": self.corrupt,
        }

    # -- protocol -------------------------------------------------------------

    async def handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = hdr.get("op")
                if op == "put":
                    self.put(hdr["key"], payload)
                    await write_frame(writer, {"ok": True})
                elif op == "get":
                    blob = self.get(hdr["key"])
                    await write_frame(
                        writer, {"ok": True, "found": blob is not None}, blob or b""
                    )
                elif op == "exists":
                    await write_frame(
                        writer, {"ok": True, "found": hdr["key"] in self._data}
                    )
                elif op == "delete":
                    blob = self._data.pop(hdr["key"], None)
                    if blob is not None:
                        self.used_bytes -= len(blob)
                    await write_frame(writer, {"ok": True, "found": blob is not None})
                elif op == "stats":
                    await write_frame(writer, {"ok": True, **self.stats()})
                elif op == "ping":
                    await write_frame(writer, {"ok": True})
                else:
                    await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})
        except Exception as e:  # keep the server alive across bad clients
            logger.warning("cache server: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass


async def serve(host: str, port: int, max_bytes: int) -> asyncio.AbstractServer:
    cs = CacheServer(max_bytes)
    server = await asyncio.start_server(cs.handle, host, port)
    logger.info("kv cache server on %s:%d (%.1f GB)", host, port, max_bytes / 1e9)
    return server


def main() -> None:
    p = argparse.ArgumentParser(description="TPU-stack remote KV cache server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--max-bytes", type=int, default=4 << 30)
    args = p.parse_args()

    async def run():
        server = await serve(args.host, args.port, args.max_bytes)
        async with server:
            await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
