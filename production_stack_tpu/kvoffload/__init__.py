"""Tiered KV-cache offload + global KV index (the stack's LMCache equivalent).

Components (SURVEY.md §7 step 5):
- serde: KV chunk (de)serialization (naive / int8).
- tiers: host-DRAM -> disk -> remote blob store per engine.
- cache_server: shared remote KV tier (standalone TCP server).
- controller: global KV-index service + clients (kvaware routing).
- connector: engine-side integration with the device page pools.
"""

from production_stack_tpu.kvoffload.connector import KVOffloadConnector
from production_stack_tpu.kvoffload.controller import (
    ControllerClient,
    KVIndexController,
    WorkerClient,
)
from production_stack_tpu.kvoffload.serde import get_serde
from production_stack_tpu.kvoffload.tiers import TieredKVStore

__all__ = [
    "KVOffloadConnector",
    "ControllerClient",
    "KVIndexController",
    "WorkerClient",
    "get_serde",
    "TieredKVStore",
]
