"""Engine-side KV offload connector.

Bridges the device page pools (engine/runner.py) to the tiered blob store
(kvoffload/tiers.py) and the KV-index controller (kvoffload/controller.py) —
the role LMCache's vLLM connector plays for the reference
(`LMCacheConnectorV1` in /root/reference
helm/templates/deployment-vllm-multi.yaml:172-186).

Data path (all on the engine device thread, no extra synchronization with the
step loop needed):
- ``save_page(pid, hash)``: device_get one page ([L, page, KH, D] k+v),
  serialize, put into the tiers; report ``admit`` to the controller.
- ``load_page(pid, hash)``: get blob from the tiers, deserialize, scatter into
  the pools in place (donated .at[].set).

Controller reporting runs on a background thread draining a queue so index
updates never block a serving step.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from production_stack_tpu.kvoffload import serde as serde_mod
from production_stack_tpu.kvoffload.serde import get_serde
from production_stack_tpu.kvoffload.tiers import TieredKVStore
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class ControllerReporter:
    """Batches admit/evict chunk-hash reports to the KV-index controller."""

    def __init__(self, controller_url: str, instance_id: str, engine_url: str,
                 page_size: int):
        from production_stack_tpu.kvoffload.controller import WorkerClient

        self.client = WorkerClient(controller_url, instance_id)
        self.engine_url = engine_url
        self.page_size = page_size
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="kv-reporter"
        )
        self._thread.start()

    def admit(self, hashes: list[str]) -> None:
        if hashes:
            self._q.put(("admit", hashes))

    def evict(self, hashes: list[str]) -> None:
        if hashes:
            self._q.put(("evict", hashes))

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=5)
        try:
            self.client.deregister()
        except Exception:
            pass
        self.client.close()

    def _run(self) -> None:
        registered = False
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            # coalesce whatever queued up behind it
            batch: dict[str, list[str]] = {"admit": [], "evict": []}
            batch[item[0]].extend(item[1])
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    return
                batch[nxt[0]].extend(nxt[1])
            try:
                if not registered:
                    self.client.register(self.engine_url, self.page_size)
                    registered = True
                if batch["admit"]:
                    self.client.admit(batch["admit"])
                if batch["evict"]:
                    self.client.evict(batch["evict"])
            except Exception as e:
                logger.warning("kv controller report failed: %s", e)
                registered = False  # re-register on reconnect


class KVOffloadConnector:
    """Wired into KVPageManager (kv.offload); owned by LLMEngine."""

    def __init__(
        self,
        runner,
        *,
        cpu_bytes: int = 0,
        disk_path: Optional[str] = None,
        disk_bytes: int = 0,
        remote_url: Optional[str] = None,
        serde: str = "naive",
        controller_url: Optional[str] = None,
        instance_id: Optional[str] = None,
        engine_url: str = "",
    ):
        self.runner = runner
        # quantized pools (runner.kv_quant, ops/quant.py): the serde
        # boundary ships the pool's OWN int8 bytes + scales (format v3) —
        # every tier, the cache server, warm starts, directory pulls, and
        # migration snapshots move the halved byte stream, and a local
        # spill + restore is bit-exact (no requant drift). The configured
        # serde would either double bytes (naive dequant) or requantize
        # lossily (int8 transport), so int8page overrides it.
        self.quant = bool(getattr(runner, "kv_quant", False))
        self.serde = get_serde("int8page" if self.quant else serde)
        self.reporter: Optional[ControllerReporter] = None
        if controller_url and instance_id:
            self.reporter = ControllerReporter(
                controller_url, instance_id, engine_url, runner.page_size
            )
        self.store = TieredKVStore(
            cpu_bytes=cpu_bytes,
            disk_path=disk_path,
            disk_bytes=disk_bytes,
            remote_url=remote_url,
            on_local_drop=self._on_local_drop,
        )
        self.saved_pages = 0
        self.loaded_pages = 0
        # device-pulled pages awaiting admission (disaggregated prefill's
        # device->device path; transfer.DeviceStaging) — consulted before the
        # host-blob tiers so admission never pays a serde round trip for them
        self.device_staging = None
        self.device_loaded_pages = 0

    def _on_local_drop(self, key: str) -> None:
        # last local copy gone; remote copies (shared server) still count as
        # "this cluster has it" but not as this instance holding it
        if self.reporter is not None:
            self.reporter.evict([key])

    # -- KVPageManager hooks (engine device thread) ---------------------------

    def _serialize_pages(self, pids: "list[int]") -> "list[bytes]":
        """Blobs for a batch of pool pages — ONE device fetch. Quantized
        pools ship their exact int8 bytes + scales (v3); fp pools go
        through the configured serde."""
        if self.quant:
            ks, vs, sks, svs = self.runner.get_pages_quant(pids)
            try:  # the fp dtype a non-quant reader should dequantize into
                dt = np.dtype(getattr(self.runner.cfg, "dtype", None))
            except TypeError:
                dt = None
            return [
                self.serde.serialize_quant(
                    np.asarray(k), np.asarray(sk), np.asarray(v),
                    np.asarray(sv), orig_dtype=dt,
                )
                for k, v, sk, sv in zip(ks, vs, sks, svs)
            ]
        ks, vs = self.runner.get_pages(pids)
        return [
            self.serde.serialize(np.asarray(k), np.asarray(v))
            for k, v in zip(ks, vs)
        ]

    def save_page(self, pid: int, h: bytes) -> None:
        """Offload one HBM page before its slot is reused. Never raises — an
        offload I/O failure (ENOSPC, remote down) must not kill the engine
        loop, which calls this from inside scheduler.schedule()."""
        try:
            if not self.store.enabled():
                # index-only mode: eviction from HBM = chunk gone from instance
                self.report_evict([h])
                return
            key = h.hex()
            if self.store.contains_local(key):
                return  # blob already offloaded (e.g. restored earlier); skip
            blob = self._serialize_pages([pid])[0]
            self.store.put(key, blob)
            self.saved_pages += 1
        except Exception:
            logger.exception("kv offload save_page failed; dropping page %s", h.hex())
            self.report_evict([h])

    def save_pages(self, pairs: "list[tuple[int, bytes]]") -> "set[bytes]":
        """Offload a batch of HBM pages before their slots are reused —
        ONE device fetch per <=64 pages instead of one per page (each fetch
        is a full host<->device round trip on network-attached chips; an
        eviction storm spilling a long history page-by-page would stall the
        engine loop for seconds). Never raises (same engine-loop safety as
        save_page). Returns the hashes whose blobs are KNOWN to be in the
        store afterwards (already local + stored this call) — a caller that
        flips pages to the zero-I/O eviction path (``offloaded``) must only
        do so for these, or a mid-batch tier failure turns into silent KV
        loss."""
        ok: "set[bytes]" = set()
        todo = pairs
        stored = 0  # prefix of `todo` safely in the store
        try:
            if not self.store.enabled():
                self.report_evict([h for _, h in pairs])
                return ok
            # pages already offloaded (contains_local) stay OUT of the evict
            # set on failure — their blobs still exist
            todo = []
            for pid, h in pairs:
                if self.store.contains_local(h.hex()):
                    ok.add(h)
                else:
                    todo.append((pid, h))
            for i in range(0, len(todo), 64):
                chunk = todo[i : i + 64]
                blobs = self._serialize_pages([pid for pid, _ in chunk])
                for (pid, h), blob in zip(chunk, blobs):
                    self.store.put(h.hex(), blob)
                    self.saved_pages += 1
                    stored += 1
                    ok.add(h)
        except Exception:
            # evict ONLY what was neither already local nor stored before
            # the failure; reporting stored pages evicted would poison the
            # global KV index for chunks this instance actually holds
            logger.exception("kv offload save_pages failed; dropping rest")
            self.report_evict([h for _, h in todo[stored:]])
        return ok

    def _deserialize_for_pool(self, blob: bytes):
        """Blob -> the tuple the runner's restore path wants: (k, v) for fp
        pools, (qk, sk, qv, sv) for quantized ones. Cross-dtype blobs
        convert at this boundary (fp blob -> host quantize; v3 blob -> fp
        dequant via the recorded serde)."""
        if self.quant:
            return serde_mod.get_serde("int8page").deserialize_quant(blob)
        return serde_mod.deserialize(blob, verify=False)

    def _set_pool_pages(self, ids: "list[int]", payloads: "list") -> None:
        if self.quant:
            self.runner.set_pages_quant(
                ids,
                [p[0] for p in payloads], [p[2] for p in payloads],
                [p[1] for p in payloads], [p[3] for p in payloads],
            )
        else:
            self.runner.set_pages(
                ids, [p[0] for p in payloads], [p[1] for p in payloads]
            )

    def load_pages(self, pairs: "list[tuple[int, bytes]]") -> int:
        """Restore a batch of pages into HBM — one upload + one scatter
        program per <=64 pages (see save_pages). Returns the length of the
        successfully restored PREFIX of ``pairs``: a vanished/unreadable blob
        truncates the chain there, matching the prefix-cache contract. Never
        raises."""
        done = 0
        batch_ids: list[int] = []
        batch_p: list = []

        def flush() -> bool:
            nonlocal done
            if not batch_ids:
                return True
            try:
                self._set_pool_pages(batch_ids, batch_p)
            except Exception:
                logger.exception("kv offload batched restore failed")
                return False
            done += len(batch_ids)
            self.loaded_pages += len(batch_ids)
            batch_ids.clear()
            batch_p.clear()
            return True

        for pid, h in pairs:
            try:
                if self.device_staging is not None and self.device_staging.contains(
                    h.hex()
                ):
                    # staged device page: flush the host batch first so the
                    # restored prefix stays in chain order, then inject
                    # through the (device-to-device) single-page path
                    if not flush():
                        return done
                    if not self.load_page(pid, h):
                        return done
                    done += 1
                    continue
                blob = self.store.get(h.hex())
                if blob is None:
                    break
                batch_ids.append(pid)
                batch_p.append(self._deserialize_for_pool(blob))
                if len(batch_ids) >= 64 and not flush():
                    return done
            except Exception:
                logger.exception("kv offload load_pages failed for %s", h.hex())
                break
        flush()
        return done

    def load_pages_sparse(self, pairs: "list[tuple[int, bytes]]") -> "list[bool]":
        """Best-effort batched restore: like :meth:`load_pages` but a
        missing/corrupt blob skips THAT page instead of truncating the rest.
        Used by warm-start restore, where entries are independent hash->page
        mappings rather than one prefix chain (a chain's later pages are
        useless without its head; a warm-start manifest's are not). Returns
        per-page success flags aligned with ``pairs``. Never raises."""
        ok = [False] * len(pairs)
        batch_idx: list[int] = []
        batch_ids: list[int] = []
        batch_p: list = []

        def flush() -> None:
            if not batch_ids:
                return
            try:
                self._set_pool_pages(batch_ids, batch_p)
            except Exception:
                logger.exception("kv warm restore batch failed")
            else:
                for i in batch_idx:
                    ok[i] = True
                self.loaded_pages += len(batch_ids)
            batch_idx.clear()
            batch_ids.clear()
            batch_p.clear()

        for i, (pid, h) in enumerate(pairs):
            try:
                blob = self.store.get(h.hex())  # verifies + quarantines
                if blob is None:
                    continue
                serde_mod.verify_blob(blob)
                batch_idx.append(i)
                batch_ids.append(pid)
                batch_p.append(self._deserialize_for_pool(blob))
                if len(batch_ids) >= 64:
                    flush()
            except Exception:
                logger.exception("kv warm restore failed for %s", h.hex())
        flush()
        return ok

    def has(self, h: bytes) -> bool:
        try:
            if self.device_staging is not None and self.device_staging.contains(h.hex()):
                return True
            return self.store.contains(h.hex())
        except Exception:
            return False

    def load_page(self, pid: int, h: bytes) -> bool:
        """Restore one page into HBM; returns False if the blob vanished or is
        unreadable. Never raises (same engine-loop safety as save_page)."""
        try:
            if self.device_staging is not None:
                staged = self.device_staging.pop(h.hex())
                if staged == "replicated":
                    # multi-host: every process holds its pulled copy in
                    # runner.kv_staged; the REPLICATED restore writes each
                    # process's pool shards — no bytes cross the host or
                    # the step stream
                    self.runner.kv_restore_page(h.hex(), pid)
                    self.device_loaded_pages += 1
                    self.loaded_pages += 1
                    return True
                if staged is not None:
                    # device->device injection: no host serde round trip
                    self.runner.set_page(pid, *staged)
                    self.device_loaded_pages += 1
                    self.loaded_pages += 1
                    return True
            blob = self.store.get(h.hex())
            if blob is None:
                return False
            if self.quant:
                self._set_pool_pages([pid], [self._deserialize_for_pool(blob)])
            else:
                k, v = serde_mod.deserialize(blob, verify=False)
                self.runner.set_page(pid, k, v)
            self.loaded_pages += 1
            return True
        except Exception:
            logger.exception("kv offload load_page failed for %s", h.hex())
            return False

    # -- controller index reporting ------------------------------------------

    def report_admit(self, hashes: list[bytes]) -> None:
        if self.reporter is not None:
            self.reporter.admit([h.hex() for h in hashes])

    def report_evict(self, hashes: list[bytes]) -> None:
        if self.reporter is not None:
            self.reporter.evict([h.hex() for h in hashes])

    def stop(self) -> None:
        if self.reporter is not None:
            self.reporter.stop()

    def stats(self) -> dict:
        return {
            "saved_pages": self.saved_pages,
            "loaded_pages": self.loaded_pages,
            **self.store.stats(),
        }
