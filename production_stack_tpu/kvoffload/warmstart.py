"""Warm-start manifests: engine restarts that keep their hot KV working set.

Every engine restart used to cold-start with an empty page pool, so the hot
shared prefixes the eviction policy fights to keep resident were recomputed
fleet-wide exactly when operators touched the system (rolling upgrade, crash,
SIGTERM rotation). LMCache ships cross-instance KV persistence for the same
reason (PAPERS.md); here the engine's own offload tier doubles as the durable
store:

- **Spill** (SIGTERM drain + periodically, so a hard crash loses only the
  delta since the last interval): the highest-reuse-score chain-head pages'
  blobs are saved through the ordinary offload path, and a small MANIFEST —
  the prefix-index metadata needed to re-admit them (chunk hash, chain depth,
  reuse score) — is written to the tier under a per-engine namespace.
- **Restore** (engine startup, before the server reports ready): the manifest
  is read back, the referenced blobs are restored into the page pool through
  the batched ``set_pages`` path, and the prefix-cache entries are rebuilt,
  so the first post-restart requests hit warm prefixes instead of recomputing
  them.

**Generation fencing.** The namespace head records a monotonically increasing
generation. A new incarnation restores from whatever the head points at, then
claims generation+1; an old incarnation still flushing (the rolling-upgrade
overlap window) re-reads the head before every spill and, on seeing a higher
generation, fences itself — its stale manifests become inert. Staleness is
never a CORRECTNESS risk (pages are content-addressed by chunk hash and every
blob is checksummed, kvoffload/serde.py), only a freshness one, which is what
the fence bounds.

Everything here runs on the engine device thread (restore during engine
construction, spills serialized with steps), so no extra locking against the
scheduler is needed.
"""

from __future__ import annotations

import json
import re
import time
from typing import Optional

from production_stack_tpu.kvoffload.serde import (
    KVIntegrityError,
    seal_bytes,
    unseal_bytes,
)
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

MANIFEST_FORMAT = 1


def _safe(ns: str) -> str:
    """Namespace -> tier-key-safe token (disk tiers use keys as filenames)."""
    return re.sub(r"[^A-Za-z0-9_.-]+", "-", ns) or "default"


class WarmStartManager:
    """Spill/restore choreography between one engine's KVPageManager and its
    offload tier. ``kv`` is the page manager, ``connector`` the
    KVOffloadConnector (blob store + batched device I/O)."""

    def __init__(
        self,
        kv,
        connector,
        *,
        namespace: str,
        interval_s: float = 60.0,
        max_pages: int = 256,
        model: str = "",
    ):
        self.kv = kv
        self.connector = connector
        self.namespace = _safe(namespace)
        self.interval_s = interval_s
        self.max_pages = max_pages
        self.model = model
        # claimed at restore(): head generation + 1 (1 on a cold tier)
        self.generation = 1
        # a higher generation appeared in the head: a newer incarnation owns
        # the namespace now; this instance must stop writing manifests
        self.fenced = False
        self.restored_pages = 0
        # age of the manifest the restore consumed (how stale the warm state
        # was), and of the newest manifest THIS incarnation wrote (how much a
        # hard crash right now would lose) — both exported on /metrics
        self.restored_manifest_age_s: Optional[float] = None
        self.last_manifest_ts: Optional[float] = None
        self.spilled_pages_total = 0
        self.stale_manifests_skipped = 0
        self._last_spill_mono = 0.0
        self._boot_mono = time.monotonic()
        # generation + write-time of the head that fenced us, for the
        # dead-fencer takeover check (see maybe_spill), plus the number of
        # consecutive head-read misses while fenced (blip tolerance)
        self._fencer_ts: Optional[float] = None
        self._fence_miss_streak = 0
        if not connector.store.durable():
            # a CPU-only tier dies with the process: spills still run (the
            # restore path is exercisable in tests) but restarts stay cold
            logger.warning(
                "warm-start: offload tier has no disk or remote level — "
                "manifests will NOT survive process death; configure "
                "--kv-offload-dir or --kv-remote-url"
            )

    # -- tier keys -----------------------------------------------------------

    @property
    def head_key(self) -> str:
        return f"ws-{self.namespace}-head"

    def manifest_key(self, generation: int) -> str:
        return f"ws-{self.namespace}-gen{generation:08d}"

    # -- envelope ------------------------------------------------------------

    def _read_json(self, key: str, attempts: int = 1) -> Optional[dict]:
        """``attempts`` > 1 retries transient misses — a remote-tier blip
        during the HEAD read must not masquerade as a cold namespace (the
        resulting generation-1 claim would invert the fence against a
        still-live older incarnation). Reads are AUTHORITATIVE (shared
        sources before this process's private caches): warm-start docs are
        mutable, and the ordinary content-addressed get-walk would hand an
        old incarnation its own stale head back — blinding it to the newer
        generation that fenced it."""
        for i in range(max(1, attempts)):
            blob = self.connector.store.get_authoritative(key)
            if blob is not None:
                try:
                    _, body = unseal_bytes(blob)
                    doc = json.loads(body)
                    return doc if isinstance(doc, dict) else None
                except (KVIntegrityError, ValueError) as e:
                    logger.warning("unreadable warm-start doc %s: %s", key, e)
                    return None
            if i + 1 < attempts:
                time.sleep(0.2)
        return None

    def _write_json(self, key: str, doc: dict) -> None:
        store = self.connector.store
        store.put(
            key, seal_bytes(json.dumps(doc).encode(), kind="warmstart")
        )
        # warm-start state must outlive the process: `put` lands in the DRAM
        # tier (disk only sees DRAM *evictions*), so force a durable local
        # copy now; the remote tier already got its write-through copy.
        # force=True: the head key is MUTABLE (generation/manifest pointer
        # updates) and a skip-if-present copy would leave the stale value
        # as the durable one
        store.persist(key, force=True)

    # -- restore (engine startup, before ready) ------------------------------

    def restore(self) -> int:
        """Pull the namespace head, restore the manifest it points at into
        the page pool, rebuild prefix-cache entries, and claim the next
        generation. Returns the number of pages restored. Never raises — a
        corrupt/absent manifest is a cold start, not a boot failure."""
        head = self._read_json(
            self.head_key,
            # remote-backed tiers can blip; a misread head means claiming
            # generation 1 under a live older incarnation — worth 3 tries
            attempts=3 if self.connector.store.remote is not None else 1,
        )
        if head is None:
            logger.info(
                "warm-start: no manifest for namespace %r (cold start, "
                "claiming generation 1)", self.namespace,
            )
            self._write_head(manifest=None)
            return 0
        prev_gen = int(head.get("generation", 0))
        self.generation = prev_gen + 1
        manifest = (
            self._read_json(head["manifest"]) if head.get("manifest") else None
        )
        restored = 0
        if manifest and int(manifest.get("format", 0)) == MANIFEST_FORMAT:
            if int(manifest.get("page_size", -1)) != self.kv.page_size:
                # page size changed across the upgrade: the chunk hashes no
                # longer line up with this engine's pages — skip wholesale
                logger.warning(
                    "warm-start: manifest page_size %s != engine %d; skipping",
                    manifest.get("page_size"), self.kv.page_size,
                )
                self.stale_manifests_skipped += 1
            else:
                entries = [
                    (bytes.fromhex(h), int(d), float(s))
                    for h, d, s in manifest.get("entries", [])
                ]
                restored = self.kv.warm_restore(
                    entries, self.connector.load_pages_sparse
                )
                self.restored_manifest_age_s = max(
                    0.0, time.time() - float(manifest.get("ts", time.time()))
                )
                logger.info(
                    "warm-start: restored %d/%d pages from generation %d "
                    "manifest (age %.1fs); serving warm",
                    restored, len(entries), prev_gen,
                    self.restored_manifest_age_s,
                )
        elif manifest is not None:
            self.stale_manifests_skipped += 1
            logger.warning("warm-start: unsupported manifest format; skipping")
        self.restored_pages = restored
        # claim the namespace NOW: a dying previous incarnation re-reads the
        # head before each spill and fences itself on our higher generation.
        # The head keeps pointing at the old manifest until our first spill,
        # so a crash before then still warm-starts from it.
        self._write_head(manifest=head.get("manifest"))
        return restored

    def _write_head(self, manifest: Optional[str]) -> None:
        try:
            self._write_json(
                self.head_key,
                {
                    "generation": self.generation,
                    "manifest": manifest,
                    "model": self.model,
                    "ts": time.time(),
                },
            )
        except Exception:  # noqa: BLE001 - tier down: warm start degrades
            logger.exception("warm-start: head write failed")

    # -- spill (periodic + SIGTERM drain) ------------------------------------

    # consecutive failed head reads before a fenced process concludes the
    # head is genuinely GONE (not a blip) and may resume; with the interval
    # gate in maybe_spill this is ~5 spill intervals of patience
    FENCE_MISS_STREAK = 5

    def _check_fence(self) -> bool:
        """True if this incarnation still owns the namespace. A missed head
        read (None) never changes the fence verdict by itself — a transient
        remote blip lifting the fence would let a stale incarnation clobber
        the live owner's head (the exact race restore()'s read-retry also
        guards); only repeated misses (see _try_takeover) conclude the head
        is really gone."""
        head = self._read_json(self.head_key)
        if head is None:
            return not self.fenced
        self._fence_miss_streak = 0
        if int(head.get("generation", 0)) > self.generation:
            if not self.fenced:
                logger.warning(
                    "warm-start: generation %d fenced by newer incarnation "
                    "(generation %d); suspending manifests from this process",
                    self.generation, head["generation"],
                )
            self.fenced = True
            self._fencer_ts = float(head.get("ts", 0.0)) or None
            return False
        if self.fenced:
            # the higher-generation head regressed: whoever fenced us is no
            # longer asserting ownership — resume
            logger.info("warm-start: fence lifted for generation %d",
                        self.generation)
            self.fenced = False
            self._fencer_ts = None
        return True

    def _try_takeover(self) -> bool:
        """Dead-fencer recovery. A LIVE newer incarnation refreshes its head
        every spill interval; a head that has not moved for several intervals
        belongs to a process that died (or a head-read blip at OUR boot made
        us claim a too-low generation — the inverted-fence case). Adopt the
        head's generation + 1 and resume, so the namespace cannot end up
        permanently writer-less. Returns True when ownership was retaken."""
        head = self._read_json(self.head_key)
        if head is None:
            # missing ≠ gone: tolerate FENCE_MISS_STREAK consecutive misses
            # (remote blips) before concluding the head vanished with its
            # writer (e.g. a DRAM-only cache server restarted)
            self._fence_miss_streak += 1
            if self._fence_miss_streak < self.FENCE_MISS_STREAK:
                return False
            logger.warning(
                "warm-start: fencing head unreadable %d times; assuming its "
                "writer is gone and resuming as generation %d",
                self._fence_miss_streak, self.generation,
            )
            self.fenced = False
            self._fencer_ts = None
            self._fence_miss_streak = 0
            return True
        self._fence_miss_streak = 0
        if int(head.get("generation", 0)) <= self.generation:
            self.fenced = False
            self._fencer_ts = None
            return True
        ts = float(head.get("ts", 0.0))
        stale_after = max(5 * max(self.interval_s, 1.0), 300.0)
        if ts and time.time() - ts > stale_after:
            self.generation = int(head["generation"]) + 1
            self.fenced = False
            self._fencer_ts = None
            logger.warning(
                "warm-start: fencing head is stale (%.0fs); taking over as "
                "generation %d", time.time() - ts, self.generation,
            )
            self._write_head(manifest=head.get("manifest"))
            return True
        self._fencer_ts = ts or self._fencer_ts
        return False

    def spill(self, reason: str = "interval") -> int:
        """Save the hottest restorable pages' blobs + a fresh manifest.
        Runs on the engine device thread. Returns pages covered by the
        manifest (0 when fenced or nothing is cached)."""
        self._last_spill_mono = time.monotonic()
        if not self._check_fence():
            return 0
        cands = self.kv.warm_candidates(self.max_pages)
        if not cands:
            return 0
        # make every manifest entry restorable: blobs not yet in the tier are
        # saved through the ordinary batched offload path. Pages are hashed
        # only once FULL, so their contents are immutable — but a page flips
        # to ``offloaded`` (the zero-I/O eviction path) ONLY when the save is
        # CONFIRMED: a mid-batch tier failure marking unsaved pages would
        # turn their later eviction into silent KV loss.
        todo = [
            (pid, h) for pid, h, _, _ in cands
            if not self.kv.pages[pid].offloaded
        ]
        saved: set = set()
        if todo:
            saved = self.connector.save_pages(todo)
            for pid, h in todo:
                if h in saved:
                    self.kv.pages[pid].offloaded = True
        # the manifest lists only restorable pages (blob known to the tier)
        entries = [
            c for c in cands
            if self.kv.pages[c[0]].offloaded or c[1] in saved
        ]
        store = self.connector.store
        if store.cpu is not None and store.disk is not None:
            # cpu+disk hierarchy: puts land in DRAM and disk only sees DRAM
            # evictions, so the manifest's blobs (hot = last to evict) would
            # die with the process — force durable copies now. No-op for
            # blobs already on disk; remote tiers got their write-through.
            for _, h, _, _ in entries:
                store.persist(h.hex())
        now = time.time()
        key = self.manifest_key(self.generation)
        try:
            self._write_json(
                key,
                {
                    "format": MANIFEST_FORMAT,
                    "generation": self.generation,
                    "model": self.model,
                    "page_size": self.kv.page_size,
                    "ts": now,
                    "entries": [
                        [h.hex(), depth, round(hits, 4)]
                        for _, h, depth, hits in entries
                    ],
                },
            )
            self._write_head(manifest=key)
        except Exception:  # noqa: BLE001 - tier down: retried next interval
            logger.exception("warm-start: manifest write failed")
            return 0
        self.last_manifest_ts = now
        self.spilled_pages_total += len(saved)
        d = getattr(self.kv, "directory", None)
        if d is not None:
            if d.generation != self.generation:
                # mid-life generation bump (dead-fencer takeover,
                # _try_takeover): the first publish under the NEW generation
                # fences EVERYTHING this engine already advertised — but the
                # process is alive and its prefix cache intact, and the
                # publisher is delta-only, so re-advertise the full live
                # working set or resident ranking to this engine silently
                # drops to zero until every page is individually re-touched
                d.generation = self.generation
                d.publish_resident([
                    (h, self.kv.pages[pid].depth, self.kv.pages[pid].hits)
                    for h, pid in self.kv.hash_to_page.items()
                ])
            # every manifest entry's blob is confirmed in the tier (and, with
            # a remote tier, write-through shared): advertise them to the
            # fleet directory under THIS generation so another engine can
            # pull this working set
            d.publish_shared([(h, dep, hits) for _, h, dep, hits in entries])
        logger.info(
            "warm-start: generation %d manifest written (%s): %d pages "
            "(%d blobs newly saved)", self.generation, reason, len(entries),
            len(saved),
        )
        return len(entries)

    def maybe_spill(self, busy: bool = False) -> int:
        """Interval-gated spill for the engine loop. A busy engine defers up
        to one extra interval so the blob save (a device fetch) doesn't land
        in the middle of a traffic burst; past 2x the interval it spills
        anyway — crash-loss must stay bounded even under sustained load.
        While fenced, each interval instead re-checks the fencing head and
        takes the namespace back once its writer is provably dead."""
        if self.interval_s <= 0:
            return 0
        age = time.monotonic() - self._last_spill_mono
        if age < self.interval_s or (busy and age < 2 * self.interval_s):
            return 0
        if self.fenced:
            self._last_spill_mono = time.monotonic()  # one head read/interval
            if not self._try_takeover():
                return 0
        return self.spill("interval")

    # -- observability -------------------------------------------------------

    def manifest_age_seconds(self) -> float:
        """Seconds since the newest manifest covering this engine's state —
        i.e. how much warm state a hard crash right now would lose. Before
        this incarnation's first spill (drain-only configs, or a failing
        tier) the restored manifest keeps AGING with uptime; reporting its
        boot-time age frozen would keep the dashboard's climbing-line alert
        from ever firing in exactly the situation it documents."""
        if self.last_manifest_ts is not None:
            return max(0.0, time.time() - self.last_manifest_ts)
        if self.restored_manifest_age_s is not None:
            return self.restored_manifest_age_s + (
                time.monotonic() - self._boot_mono
            )
        return -1.0  # no manifest has ever existed for this namespace

    def stats(self) -> dict:
        return {
            "warm_start_restored_pages": self.restored_pages,
            "warm_start_manifest_age_seconds": round(
                self.manifest_age_seconds(), 3
            ),
            "warm_start_spilled_pages_total": self.spilled_pages_total,
            "warm_start_generation": self.generation,
            "warm_start_fenced": int(self.fenced),
            "warm_start_stale_manifests_skipped_total": (
                self.stale_manifests_skipped
            ),
        }
