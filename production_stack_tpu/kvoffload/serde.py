"""KV chunk serializers (serde) for the offload tiers.

A *chunk* is one KV page across all layers: ``k, v: [L, page_size, KH, D]``.
Two serdes, mirroring the reference's LMCache serde choice
(`LMCACHE_REMOTE_SERDE` env, /root/reference
helm/templates/deployment-vllm-multi.yaml:309-314):

- ``naive``: raw bytes, zero loss, highest bandwidth need.
- ``int8``: per-(layer, head) symmetric int8 quantization (CacheGen-style
  compression, lossy but ~2x smaller than bf16) for DCN/disk tiers.

Blob layout: ``u32 header_len | header JSON | k bytes | v bytes``.
"""

from __future__ import annotations

import json
import struct

import numpy as np

try:  # bfloat16 numpy dtype ships with jax
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    BF16 = np.dtype(np.float32)

_HDR = struct.Struct("!I")


def _dtype_name(dt: np.dtype) -> str:
    return "bfloat16" if dt == BF16 else np.dtype(dt).name


def _dtype_of(name: str) -> np.dtype:
    return BF16 if name == "bfloat16" else np.dtype(name)


class NaiveSerde:
    """Lossless raw-bytes serde."""

    name = "naive"

    def serialize(self, k: np.ndarray, v: np.ndarray) -> bytes:
        hdr = json.dumps(
            {
                "serde": self.name,
                "shape": list(k.shape),
                "dtype": _dtype_name(k.dtype),
            }
        ).encode()
        return _HDR.pack(len(hdr)) + hdr + k.tobytes() + v.tobytes()

    @staticmethod
    def _split(blob: bytes) -> tuple[dict, memoryview]:
        (n,) = _HDR.unpack_from(blob)
        hdr = json.loads(blob[_HDR.size : _HDR.size + n])
        return hdr, memoryview(blob)[_HDR.size + n :]

    def deserialize(self, blob: bytes) -> tuple[np.ndarray, np.ndarray]:
        hdr, body = self._split(blob)
        dt = _dtype_of(hdr["dtype"])
        shape = tuple(hdr["shape"])
        nbytes = int(np.prod(shape)) * dt.itemsize
        k = np.frombuffer(body[:nbytes], dt).reshape(shape)
        v = np.frombuffer(body[nbytes : 2 * nbytes], dt).reshape(shape)
        return k, v


class Int8Serde(NaiveSerde):
    """Symmetric int8 quantization per (layer, kv-head): amax scale stored
    fp32. Halves bytes vs bf16 at <1% relative error on KV magnitudes."""

    name = "int8"

    @staticmethod
    def _quant(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        # x: [L, page, KH, D] -> scales [L, 1, KH, 1]
        xf = x.astype(np.float32)
        amax = np.abs(xf).max(axis=(1, 3), keepdims=True)
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.round(xf / scale), -127, 127).astype(np.int8)
        return q, scale

    def serialize(self, k: np.ndarray, v: np.ndarray) -> bytes:
        qk, sk = self._quant(k)
        qv, sv = self._quant(v)
        hdr = json.dumps(
            {
                "serde": self.name,
                "shape": list(k.shape),
                "dtype": _dtype_name(k.dtype),
            }
        ).encode()
        return (
            _HDR.pack(len(hdr))
            + hdr
            + sk.tobytes()
            + qk.tobytes()
            + sv.tobytes()
            + qv.tobytes()
        )

    def deserialize(self, blob: bytes) -> tuple[np.ndarray, np.ndarray]:
        hdr, body = self._split(blob)
        shape = tuple(hdr["shape"])
        L, page, KH, D = shape
        dt = _dtype_of(hdr["dtype"])
        sbytes = L * KH * 4
        qbytes = int(np.prod(shape))

        def dequant(mv):
            s = np.frombuffer(mv[:sbytes], np.float32).reshape(L, 1, KH, 1)
            q = np.frombuffer(mv[sbytes : sbytes + qbytes], np.int8).reshape(shape)
            return (q.astype(np.float32) * s).astype(dt)

        k = dequant(body)
        v = dequant(body[sbytes + qbytes :])
        return k, v


SERDES = {"naive": NaiveSerde, "int8": Int8Serde}


def get_serde(name: str):
    try:
        return SERDES[name]()
    except KeyError:
        raise ValueError(f"unknown serde {name!r}; options: {sorted(SERDES)}")


def deserialize(blob: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Deserialize by the serde name recorded in the blob header — blobs from
    engines with a different configured serde (shared cache server, or a disk
    tier surviving a serde change) parse correctly."""
    hdr, _ = NaiveSerde._split(blob)
    return get_serde(hdr.get("serde", "naive")).deserialize(blob)
