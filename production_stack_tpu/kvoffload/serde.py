"""KV chunk serializers (serde) for the offload tiers.

A *chunk* is one KV page across all layers: ``k, v: [L, page_size, KH, D]``.
Two serdes, mirroring the reference's LMCache serde choice
(`LMCACHE_REMOTE_SERDE` env, /root/reference
helm/templates/deployment-vllm-multi.yaml:309-314):

- ``naive``: raw bytes, zero loss, highest bandwidth need.
- ``int8``: per-(layer, head) symmetric int8 quantization (CacheGen-style
  compression, lossy but ~2x smaller than bf16) for DCN/disk tiers.

- ``int8page``: the QUANTIZED-POOL passthrough serde (format v3): when the
  engine runs ``kv_cache_dtype=int8`` (ops/quant.py) the pool already holds
  int8 pages + per-page per-kv-head scales, and this serde ships those
  EXACT bytes — no dequant/requant round trip, and every KV hop (offload
  tiers, cache server, warm-start manifests, directory pulls, migration
  snapshots) moves the halved byte stream. The scales travel INSIDE the
  blob body, CRC-framed with it, and ``split_kv_heads_quant`` /
  ``join_kv_heads_quant`` keep the blobs tp-invariant like fp ones.

Blob layout: ``u32 header_len | header JSON | body``.

Integrity (format v2+): the header additionally records ``v`` (format
version), ``blen`` (body length) and ``crc`` (CRC32 of the body). Readers
call :func:`verify_blob` before trusting a blob pulled from any tier — a
bit-flipped or truncated page must convert to a cache MISS (recompute), never
to silently-wrong KV. v1 blobs (no ``crc``) still parse, so a disk tier
surviving an upgrade keeps serving; a blob from a FUTURE format version is
rejected as unreadable rather than misparsed (a v2-era reader refuses v3
quantized blobs instead of misparsing their scales as KV).
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

try:  # bfloat16 numpy dtype ships with jax
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    BF16 = np.dtype(np.float32)

_HDR = struct.Struct("!I")

# blob format version written by this build; readers accept <= this.
# v3 adds the quantized-page body layout (int8page serde).
SERDE_FORMAT_VERSION = 3


class KVIntegrityError(ValueError):
    """A blob failed its checksum / length / version check. The caller must
    treat the entry as a miss (quarantine + recompute), never deserialize."""


def _seal(hdr: dict, body: bytes, version: int = 2) -> bytes:
    """Finish a blob: stamp version + body length + CRC32 into the header.

    ``version`` is the MINIMUM format version able to parse this blob —
    fp blobs keep stamping v2 so a mixed-version fleet's older readers
    still accept them during a rolling upgrade; only quantized-page blobs
    (whose body layout is new) claim v3 and get refused by old readers
    instead of misparsed."""
    hdr["v"] = version
    hdr["blen"] = len(body)
    hdr["crc"] = zlib.crc32(body) & 0xFFFFFFFF
    enc = json.dumps(hdr).encode()
    return _HDR.pack(len(enc)) + enc + body


def verify_blob(blob: bytes) -> dict:
    """Integrity-check a blob without deserializing its payload; returns the
    parsed header. Raises :class:`KVIntegrityError` on a malformed frame, a
    future format version, a truncated body, or a CRC mismatch. v1 blobs
    (no ``crc`` field) pass — they predate checksums."""
    try:
        (n,) = _HDR.unpack_from(blob)
        hdr = json.loads(bytes(blob[_HDR.size : _HDR.size + n]))
        if not isinstance(hdr, dict):
            raise ValueError("header is not an object")
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        raise KVIntegrityError(f"unreadable blob header: {e}") from None
    version = int(hdr.get("v", 1))
    if version > SERDE_FORMAT_VERSION:
        raise KVIntegrityError(
            f"blob format v{version} is newer than supported "
            f"v{SERDE_FORMAT_VERSION}"
        )
    body = memoryview(blob)[_HDR.size + n :]
    if "blen" in hdr and len(body) != int(hdr["blen"]):
        raise KVIntegrityError(
            f"truncated blob: body {len(body)} bytes, header says {hdr['blen']}"
        )
    if "crc" in hdr and (zlib.crc32(body) & 0xFFFFFFFF) != int(hdr["crc"]):
        raise KVIntegrityError("blob CRC mismatch (corrupt payload)")
    return hdr


def seal_bytes(payload: bytes, kind: str = "raw", **attrs) -> bytes:
    """Wrap arbitrary bytes in the same verifiable envelope KV pages use —
    non-page tier entries (warm-start manifests, head pointers) get the same
    corruption detection as page blobs."""
    return _seal({"kind": kind, **attrs}, payload)


def unseal_bytes(blob: bytes) -> tuple[dict, bytes]:
    """Verify and open a :func:`seal_bytes` envelope; returns (header, body).
    Raises :class:`KVIntegrityError` on corruption."""
    hdr = verify_blob(blob)
    (n,) = _HDR.unpack_from(blob)
    return hdr, bytes(memoryview(blob)[_HDR.size + n :])


def _dtype_name(dt: np.dtype) -> str:
    return "bfloat16" if dt == BF16 else np.dtype(dt).name


def _dtype_of(name: str) -> np.dtype:
    return BF16 if name == "bfloat16" else np.dtype(name)


class NaiveSerde:
    """Lossless raw-bytes serde."""

    name = "naive"

    def serialize(self, k: np.ndarray, v: np.ndarray) -> bytes:
        hdr = {
            "serde": self.name,
            "shape": list(k.shape),
            "dtype": _dtype_name(k.dtype),
        }
        return _seal(hdr, k.tobytes() + v.tobytes())

    @staticmethod
    def _split(blob: bytes) -> tuple[dict, memoryview]:
        (n,) = _HDR.unpack_from(blob)
        hdr = json.loads(blob[_HDR.size : _HDR.size + n])
        return hdr, memoryview(blob)[_HDR.size + n :]

    def deserialize(self, blob: bytes) -> tuple[np.ndarray, np.ndarray]:
        hdr, body = self._split(blob)
        dt = _dtype_of(hdr["dtype"])
        shape = tuple(hdr["shape"])
        nbytes = int(np.prod(shape)) * dt.itemsize
        k = np.frombuffer(body[:nbytes], dt).reshape(shape)
        v = np.frombuffer(body[nbytes : 2 * nbytes], dt).reshape(shape)
        return k, v


class Int8Serde(NaiveSerde):
    """Symmetric int8 quantization per (layer, kv-head): amax scale stored
    fp32. Halves bytes vs bf16 at <1% relative error on KV magnitudes."""

    name = "int8"

    @staticmethod
    def _quant(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        # x: [L, page, KH, D] -> scales [L, 1, KH, 1]
        xf = x.astype(np.float32)
        amax = np.abs(xf).max(axis=(1, 3), keepdims=True)
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.round(xf / scale), -127, 127).astype(np.int8)
        return q, scale

    def serialize(self, k: np.ndarray, v: np.ndarray) -> bytes:
        qk, sk = self._quant(k)
        qv, sv = self._quant(v)
        hdr = {
            "serde": self.name,
            "shape": list(k.shape),
            "dtype": _dtype_name(k.dtype),
        }
        return _seal(
            hdr, sk.tobytes() + qk.tobytes() + sv.tobytes() + qv.tobytes()
        )

    def deserialize(self, blob: bytes) -> tuple[np.ndarray, np.ndarray]:
        hdr, body = self._split(blob)
        shape = tuple(hdr["shape"])
        L, page, KH, D = shape
        dt = _dtype_of(hdr["dtype"])
        sbytes = L * KH * 4
        qbytes = int(np.prod(shape))

        def dequant(mv):
            s = np.frombuffer(mv[:sbytes], np.float32).reshape(L, 1, KH, 1)
            q = np.frombuffer(mv[sbytes : sbytes + qbytes], np.int8).reshape(shape)
            return (q.astype(np.float32) * s).astype(dt)

        k = dequant(body)
        v = dequant(body[sbytes + qbytes :])
        return k, v


class Int8PageSerde(NaiveSerde):
    """Quantized-POOL passthrough serde (format v3, ops/quant.py contract).

    Unlike :class:`Int8Serde` — which quantizes an fp page at serialize
    time and dequantizes at deserialize time (a lossy transport encoding) —
    this serde ships the pool's OWN int8 bytes and per-page per-kv-head
    scales verbatim: ``serialize_quant``/``deserialize_quant`` round-trip
    bit-exactly, so a spill + restore on a quantized engine reproduces the
    exact pool state (no requant drift), and every tier/hop moves half the
    bytes. ``deserialize`` (the generic fp entry point) dequantizes, so a
    NON-quantized engine pulling a v3 blob from the shared tier still gets
    usable fp KV; ``serialize``/``deserialize_quant`` quantize/accept fp
    input, covering the other cross-dtype direction.

    Body layout: ``sk [L, KH] f32 | qk [L, page, KH, D] int8 | sv | qv``.
    """

    name = "int8page"

    def serialize(self, k: np.ndarray, v: np.ndarray) -> bytes:
        from production_stack_tpu.ops.quant import quantize_page_host

        qk, sk = quantize_page_host(np.asarray(k))
        qv, sv = quantize_page_host(np.asarray(v))
        return self.serialize_quant(qk, sk, qv, sv, orig_dtype=k.dtype)

    def serialize_quant(
        self, qk: np.ndarray, sk: np.ndarray, qv: np.ndarray, sv: np.ndarray,
        orig_dtype=None,
    ) -> bytes:
        """Pool bytes in, blob out — zero-copy of the quantized state."""
        hdr = {
            "serde": self.name,
            "shape": list(qk.shape),
            "dtype": _dtype_name(
                np.dtype(orig_dtype) if orig_dtype is not None else BF16
            ),
        }
        body = (
            np.ascontiguousarray(sk, np.float32).tobytes()
            + np.ascontiguousarray(qk, np.int8).tobytes()
            + np.ascontiguousarray(sv, np.float32).tobytes()
            + np.ascontiguousarray(qv, np.int8).tobytes()
        )
        return _seal(hdr, body, version=3)

    @staticmethod
    def _split_quant(blob: bytes):
        hdr, body = NaiveSerde._split(blob)
        L, page, KH, D = hdr["shape"]
        sbytes = L * KH * 4
        qbytes = L * page * KH * D

        def part(off):
            s = np.frombuffer(body[off : off + sbytes], np.float32)
            q = np.frombuffer(
                body[off + sbytes : off + sbytes + qbytes], np.int8
            )
            return (
                q.reshape(L, page, KH, D),
                s.reshape(L, KH),
            )

        qk, sk = part(0)
        qv, sv = part(sbytes + qbytes)
        return hdr, qk, sk, qv, sv

    def deserialize_quant(self, blob: bytes):
        """(qk, sk, qv, sv) — the exact pool bytes. Accepts fp blobs from
        other serdes too (cross-dtype restore): those quantize host-side
        with fresh per-page scales."""
        hdr, _ = NaiveSerde._split(blob)
        if hdr.get("serde") != self.name:
            from production_stack_tpu.ops.quant import quantize_page_host

            k, v = get_serde(hdr.get("serde", "naive")).deserialize(blob)
            qk, sk = quantize_page_host(np.asarray(k))
            qv, sv = quantize_page_host(np.asarray(v))
            return qk, sk, qv, sv
        _, qk, sk, qv, sv = self._split_quant(blob)
        return qk, sk, qv, sv

    def deserialize(self, blob: bytes) -> tuple[np.ndarray, np.ndarray]:
        from production_stack_tpu.ops.quant import dequantize_page_host

        hdr, qk, sk, qv, sv = self._split_quant(blob)
        dt = _dtype_of(hdr["dtype"])
        return (
            dequantize_page_host(qk, sk, dt),
            dequantize_page_host(qv, sv, dt),
        )


# -- tensor-parallel shard boundary -------------------------------------------
#
# Under tensor parallelism the device pool holds one KV-HEAD SHARD of every
# page per chip (parallel/shardings.KV_PAGES_SPEC); the offload tiers hold
# whole logical pages. The gather/scatter between the two happens at this
# serde boundary: runner.get_pages lays the page out replicated (the
# all-gather rides ICI) before serialize, and set_pages scatters the
# deserialized page back into the tp-sharded pool device-side. Blobs are
# therefore tp-INVARIANT: a page spilled by a tp=4 engine restores into a
# tp=1 or tp=2 engine bit-identically (warm starts, migration snapshots, and
# directory pulls all cross tp shapes freely — docs/multichip-serving.md).
# The helpers below express one logical page <-> N head-shards for staging
# and for shard-consistency checks in tests.


def split_kv_heads(
    k: np.ndarray, v: np.ndarray, shards: int
) -> "list[tuple[np.ndarray, np.ndarray]]":
    """Split one logical page's K/V ``[L, page, KH, D]`` into ``shards``
    contiguous head-shards (shard i holds kv heads ``[i*KH/N, (i+1)*KH/N)``
    — the same contiguous split NamedSharding uses for the pool's KH axis).
    KH must divide evenly; the pool replicates instead when it cannot
    (runner._kv_sharding), and whole-page blobs need no split."""
    KH = k.shape[2]
    if KH % shards:
        raise ValueError(f"cannot split {KH} kv heads into {shards} shards")
    return list(zip(np.split(k, shards, axis=2), np.split(v, shards, axis=2)))


def join_kv_heads(
    parts: "list[tuple[np.ndarray, np.ndarray]]",
) -> tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`split_kv_heads`: reassemble a logical page from its
    head-shards (shard order = head order)."""
    return (
        np.concatenate([k for k, _ in parts], axis=2),
        np.concatenate([v for _, v in parts], axis=2),
    )


def split_kv_heads_quant(
    qk: np.ndarray, sk: np.ndarray, qv: np.ndarray, sv: np.ndarray,
    shards: int,
) -> "list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]":
    """Quantized twin of :func:`split_kv_heads`: per-kv-head scales split
    ALONG their heads (axis 1 of [L, KH]) exactly like the page bytes'
    KH axis, so a tp=4 engine's shard i carries precisely the scales for
    its heads — blobs stay tp-invariant under int8 too."""
    KH = qk.shape[2]
    if KH % shards:
        raise ValueError(f"cannot split {KH} kv heads into {shards} shards")
    return [
        (k, s_k, v, s_v)
        for (k, s_k), (v, s_v) in zip(
            zip(np.split(qk, shards, axis=2), np.split(sk, shards, axis=1)),
            zip(np.split(qv, shards, axis=2), np.split(sv, shards, axis=1)),
        )
    ]


def join_kv_heads_quant(
    parts: "list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]",
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Inverse of :func:`split_kv_heads_quant` (shard order = head order)."""
    return (
        np.concatenate([p[0] for p in parts], axis=2),
        np.concatenate([p[1] for p in parts], axis=1),
        np.concatenate([p[2] for p in parts], axis=2),
        np.concatenate([p[3] for p in parts], axis=1),
    )


SERDES = {"naive": NaiveSerde, "int8": Int8Serde, "int8page": Int8PageSerde}


def get_serde(name: str):
    try:
        return SERDES[name]()
    except KeyError:
        raise ValueError(f"unknown serde {name!r}; options: {sorted(SERDES)}")


def deserialize(blob: bytes, verify: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Deserialize by the serde name recorded in the blob header — blobs from
    engines with a different configured serde (shared cache server, or a disk
    tier surviving a serde change) parse correctly. Verifies the checksum
    first — a corrupt blob raises :class:`KVIntegrityError` instead of
    producing silently-wrong KV; pass ``verify=False`` only when the blob
    just came from a read path that already verified it (TieredKVStore.get),
    to avoid paying the CRC twice on the hot restore path."""
    if verify:
        hdr = verify_blob(blob)
    else:
        hdr, _ = NaiveSerde._split(blob)
    return get_serde(hdr.get("serde", "naive")).deserialize(blob)
