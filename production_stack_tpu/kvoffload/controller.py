"""KV-index controller — the global "which engine has which KV chunks" service.

TPU-native replacement for the LMCache controller the reference router queries
for KV-aware routing (/root/reference src/vllm_router/routers/routing_logic.py
:228-329: `LookupMsg(tokens)` -> instance_id, `QueryInstMsg(ip)`; engines run a
worker that reports chunk admissions/evictions). Here:

- Engines register ``(instance_id, url, page_size)`` and stream
  ``admit``/``evict`` batches of chunk-hash hexes
  (kvoffload/connector.py ControllerReporter).
- The router's KvawareRouter sends ``lookup`` with token ids; the controller
  recomputes the rolling chunk-hash chain (engine/kv_manager.prefix_hashes —
  the SAME hash as the engine prefix cache, SURVEY.md §7 hard part #3) and
  returns the instance holding the longest contiguous prefix.

Run: ``python -m production_stack_tpu.kvoffload.controller --port 9000``.
"""

from __future__ import annotations

import argparse
import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from production_stack_tpu.engine.kv_manager import prefix_hashes
from production_stack_tpu.kvoffload.protocol import (
    BlockingClient,
    parse_hostport,
    read_frame,
    write_frame,
)
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

DEFAULT_PAGE_SIZE = 16


@dataclass
class InstanceState:
    url: str
    page_size: int
    chunks: set[str] = field(default_factory=set)
    last_seen: float = field(default_factory=time.monotonic)


class KVIndexController:
    """In-memory chunk index. Single asyncio loop — no locking needed."""

    def __init__(self, instance_timeout: float = 120.0):
        self.instances: dict[str, InstanceState] = {}
        self.chunk_holders: dict[str, set[str]] = {}
        self.instance_timeout = instance_timeout
        self.lookups = 0
        self.lookup_hits = 0

    # -- index ops ------------------------------------------------------------

    def register(self, instance_id: str, url: str, page_size: int) -> None:
        prev = self.instances.get(instance_id)
        if prev is not None and prev.url != url:
            self.deregister(instance_id)
            prev = None
        if prev is None:
            self.instances[instance_id] = InstanceState(url, page_size)
            logger.info("registered instance %s at %s", instance_id, url)
        else:
            prev.last_seen = time.monotonic()

    def deregister(self, instance_id: str) -> None:
        st = self.instances.pop(instance_id, None)
        if st is None:
            return
        for h in st.chunks:
            holders = self.chunk_holders.get(h)
            if holders is not None:
                holders.discard(instance_id)
                if not holders:
                    del self.chunk_holders[h]
        logger.info("deregistered instance %s", instance_id)

    def admit(self, instance_id: str, hashes: list[str]) -> None:
        st = self.instances.get(instance_id)
        if st is None:
            return
        st.last_seen = time.monotonic()
        for h in hashes:
            st.chunks.add(h)
            self.chunk_holders.setdefault(h, set()).add(instance_id)

    def evict(self, instance_id: str, hashes: list[str]) -> None:
        st = self.instances.get(instance_id)
        if st is None:
            return
        st.last_seen = time.monotonic()
        for h in hashes:
            st.chunks.discard(h)
            holders = self.chunk_holders.get(h)
            if holders is not None:
                holders.discard(instance_id)
                if not holders:
                    del self.chunk_holders[h]

    def _expire(self) -> None:
        now = time.monotonic()
        for iid in [
            i
            for i, st in self.instances.items()
            if now - st.last_seen > self.instance_timeout
        ]:
            self.deregister(iid)

    def lookup(self, tokens: list[int], page_size: Optional[int] = None) -> dict:
        """Longest contiguous chunk-chain prefix across instances.

        Instances may use different page sizes, so the hash chain is computed
        per distinct page size and each instance is scored against its own
        chain; the comparison metric is *matched tokens*, not chunks."""
        self._expire()
        self.lookups += 1
        by_ps: dict[int, list[str]] = {}
        for st in self.instances.values():
            ps = page_size or st.page_size
            if ps not in by_ps:
                by_ps[ps] = [h.hex() for h in prefix_hashes(tokens, ps)]
        best_inst, best_tokens, best_chunks, best_total = None, 0, 0, 0
        for inst, st in self.instances.items():
            ps = page_size or st.page_size
            hashes = by_ps[ps]
            n = 0
            for h in hashes:
                if inst not in self.chunk_holders.get(h, ()):
                    break
                n += 1
            if n * ps > best_tokens:
                best_inst, best_tokens = inst, n * ps
                best_chunks, best_total = n, len(hashes)
        if best_inst is None:
            return {"instance_id": None, "url": None, "matched_chunks": 0}
        self.lookup_hits += 1
        return {
            "instance_id": best_inst,
            "url": self.instances[best_inst].url,
            "matched_chunks": best_chunks,
            "matched_tokens": best_tokens,
            "total_chunks": best_total,
        }

    def stats(self) -> dict:
        return {
            "instances": {
                i: {"url": st.url, "chunks": len(st.chunks)}
                for i, st in self.instances.items()
            },
            "indexed_chunks": len(self.chunk_holders),
            "lookups": self.lookups,
            "lookup_hits": self.lookup_hits,
        }

    # -- protocol -------------------------------------------------------------

    async def handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, _ = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = hdr.get("op")
                if op == "register":
                    self.register(
                        hdr["instance_id"],
                        hdr["url"],
                        hdr.get("page_size", DEFAULT_PAGE_SIZE),
                    )
                    await write_frame(writer, {"ok": True})
                elif op == "deregister":
                    self.deregister(hdr["instance_id"])
                    await write_frame(writer, {"ok": True})
                elif op == "admit":
                    self.admit(hdr["instance_id"], hdr["hashes"])
                    await write_frame(writer, {"ok": True})
                elif op == "evict":
                    self.evict(hdr["instance_id"], hdr["hashes"])
                    await write_frame(writer, {"ok": True})
                elif op == "lookup":
                    res = self.lookup(hdr["tokens"], hdr.get("page_size"))
                    await write_frame(writer, {"ok": True, **res})
                # graftcheck: disable=GC009 — reference-parity op (the upstream controller's QueryInstMsg); kept wire-compatible for external clients, no first-party caller by design
                elif op == "query_inst":
                    # reference parity: QueryInstMsg(ip) -> instance url
                    st = self.instances.get(hdr["instance_id"])
                    await write_frame(
                        writer, {"ok": True, "url": st.url if st else None}
                    )
                elif op == "stats":
                    await write_frame(writer, {"ok": True, **self.stats()})
                elif op == "ping":
                    await write_frame(writer, {"ok": True})
                else:
                    await write_frame(writer, {"ok": False, "error": f"bad op {op!r}"})
        except Exception as e:
            logger.warning("kv controller: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:
                pass


async def serve(host: str, port: int) -> asyncio.AbstractServer:
    ctl = KVIndexController()
    server = await asyncio.start_server(ctl.handle, host, port)
    logger.info("kv-index controller on %s:%d", host, port)
    return server


# -- clients ------------------------------------------------------------------


class ControllerClient:
    """Asyncio client used by the router's KvawareRouter."""

    def __init__(self, url: str, timeout: float = 5.0):
        self.host, self.port = parse_hostport(url, default_port=9000)
        self.timeout = timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def _request(self, header: dict) -> dict:
        async with self._lock:
            try:
                if self._writer is None:
                    self._reader, self._writer = await asyncio.wait_for(
                        asyncio.open_connection(self.host, self.port), self.timeout
                    )
                await write_frame(self._writer, header)
                hdr, _ = await asyncio.wait_for(read_frame(self._reader), self.timeout)
                return hdr
            except Exception:
                await self.close()
                raise

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._reader = self._writer = None

    async def lookup(self, tokens: list[int]) -> dict:
        return await self._request({"op": "lookup", "tokens": tokens})

    async def lookup_url(self, tokens: list[int]) -> Optional[str]:
        return (await self.lookup(tokens)).get("url")

    async def stats(self) -> dict:
        return await self._request({"op": "stats"})


class WorkerClient(BlockingClient):
    """Blocking client for the engine-side reporting thread."""

    def __init__(self, url: str, instance_id: str, timeout: float = 10.0):
        host, port = parse_hostport(url, default_port=9000)
        super().__init__(host, port, timeout=timeout)
        self.instance_id = instance_id

    def register(self, engine_url: str, page_size: int) -> None:
        self.request(
            {
                "op": "register",
                "instance_id": self.instance_id,
                "url": engine_url,
                "page_size": page_size,
            }
        )

    def admit(self, hashes: list[str]) -> None:
        self.request({"op": "admit", "instance_id": self.instance_id, "hashes": hashes})

    def evict(self, hashes: list[str]) -> None:
        self.request({"op": "evict", "instance_id": self.instance_id, "hashes": hashes})

    def deregister(self) -> None:
        self.request({"op": "deregister", "instance_id": self.instance_id})


def main() -> None:
    p = argparse.ArgumentParser(description="TPU-stack KV-index controller")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9000)
    args = p.parse_args()

    async def run():
        server = await serve(args.host, args.port)
        async with server:
            await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
