"""Tiered KV blob store: host-DRAM -> local disk -> remote cache server.

The LMCache-equivalent storage hierarchy the reference configures per engine
(/root/reference helm/templates/deployment-vllm-multi.yaml:297-314:
`LMCACHE_MAX_LOCAL_CPU_SIZE`, `LMCACHE_MAX_LOCAL_DISK_SIZE` + path,
`LMCACHE_REMOTE_URL` + serde). Keys are chunk-hash hex strings (the same
rolling hashes as engine/kv_manager.py and the router trie); values are
serde blobs.

Policy: ``put`` writes to DRAM (and through to the remote tier so other
instances can share); DRAM eviction spills to disk; disk eviction drops the
blob locally. ``get`` walks DRAM -> disk -> remote and promotes hits to DRAM.
Evictions that remove the *last local* copy surface through ``on_local_drop``
so the engine can tell the KV-index controller.

Integrity: every ``get`` verifies the blob's checksum/version header
(kvoffload/serde.py v2 format) before returning it. A corrupt or
future-version blob is QUARANTINED — deleted from the tier that served it,
counted in ``corrupt_pages`` (exported as vllm:kv_corrupt_pages_total) — and
the walk continues to the next tier, so a bit-flip on disk falls back to the
remote copy and, failing that, to recompute. A bad page is never served.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

from production_stack_tpu.kvoffload.protocol import BlockingClient, parse_hostport
from production_stack_tpu.kvoffload.serde import KVIntegrityError, verify_blob
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class CPUTier:
    """Byte-capped LRU in host DRAM."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._data: OrderedDict[str, bytes] = OrderedDict()
        self.used_bytes = 0

    def get(self, key: str) -> Optional[bytes]:
        blob = self._data.get(key)
        if blob is not None:
            self._data.move_to_end(key)
        return blob

    def put(self, key: str, blob: bytes) -> list[tuple[str, bytes]]:
        """Insert; returns evicted (key, blob) pairs for spill-down."""
        if len(blob) > self.max_bytes:
            return [(key, blob)]
        if key in self._data:
            self.used_bytes -= len(self._data[key])
            del self._data[key]
        self._data[key] = blob
        self.used_bytes += len(blob)
        evicted = []
        while self.used_bytes > self.max_bytes:
            k, b = self._data.popitem(last=False)
            self.used_bytes -= len(b)
            evicted.append((k, b))
        return evicted

    def delete(self, key: str) -> None:
        blob = self._data.pop(key, None)
        if blob is not None:
            self.used_bytes -= len(blob)

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)


class DiskTier:
    """Byte-capped LRU of blob files in a directory."""

    def __init__(self, path: str, max_bytes: int):
        self.path = path
        self.max_bytes = max_bytes
        os.makedirs(path, exist_ok=True)
        self._index: OrderedDict[str, int] = OrderedDict()  # key -> size
        self.used_bytes = 0
        for name in sorted(os.listdir(path)):  # recover after restart
            if name.endswith(".kv"):
                size = os.path.getsize(os.path.join(path, name))
                self._index[name[:-3]] = size
                self.used_bytes += size

    def _file(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.kv")

    def get(self, key: str) -> Optional[bytes]:
        if key not in self._index:
            return None
        try:
            with open(self._file(key), "rb") as f:
                blob = f.read()
        except OSError:
            self.delete(key)
            return None
        self._index.move_to_end(key)
        return blob

    def get_fresh(self, key: str) -> Optional[bytes]:
        """Read the file directly, bypassing this process's in-memory index:
        a concurrent incarnation sharing the directory (rolling upgrade on
        one host) may have written the key after our index was built. Does
        not touch index/LRU state — mutable-key reads must stay side-effect
        free."""
        try:
            with open(self._file(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def put(self, key: str, blob: bytes) -> list[str]:
        """Write; returns keys evicted (dropped entirely)."""
        if len(blob) > self.max_bytes:
            return [key]
        self.delete(key)
        tmp = self._file(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self._file(key))
        self._index[key] = len(blob)
        self.used_bytes += len(blob)
        dropped = []
        while self.used_bytes > self.max_bytes:
            k, size = self._index.popitem(last=False)
            self.used_bytes -= size
            try:
                os.unlink(self._file(k))
            except OSError:
                pass
            dropped.append(k)
        return dropped

    def delete(self, key: str) -> None:
        size = self._index.pop(key, None)
        if size is not None:
            self.used_bytes -= size
            try:
                os.unlink(self._file(key))
            except OSError:
                pass

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def __len__(self) -> int:
        return len(self._index)


class RemoteTier:
    """Client view of the shared cache server (kvoffload/cache_server.py)."""

    def __init__(self, url: str, timeout: float = 10.0):
        host, port = parse_hostport(url, default_port=8200)
        self._client = BlockingClient(host, port, timeout=timeout)
        self._lock = threading.Lock()
        self.errors = 0

    def _request(self, header: dict, payload: bytes = b"") -> tuple[dict, bytes]:
        with self._lock:
            return self._client.request(header, payload)

    def get(self, key: str) -> Optional[bytes]:
        try:
            hdr, body = self._request({"op": "get", "key": key})
            return body if hdr.get("ok") and hdr.get("found") else None
        except Exception as e:
            self.errors += 1
            logger.warning("remote kv get failed: %s", e)
            return None

    def put(self, key: str, blob: bytes) -> None:
        try:
            self._request({"op": "put", "key": key}, blob)
        except Exception as e:
            self.errors += 1
            logger.warning("remote kv put failed: %s", e)

    def __contains__(self, key: str) -> bool:
        try:
            hdr, _ = self._request({"op": "exists", "key": key})
            return bool(hdr.get("found"))
        except Exception:
            self.errors += 1
            return False

    def delete(self, key: str) -> None:
        """Quarantine support: drop a corrupt entry server-side so other
        engines sharing the cache server stop fetching it too."""
        try:
            self._request({"op": "delete", "key": key})
        except Exception as e:
            self.errors += 1
            logger.warning("remote kv delete failed: %s", e)

    def close(self) -> None:
        self._client.close()


class TieredKVStore:
    """The per-engine offload hierarchy. Thread-safe for the engine loop +
    background reporters."""

    def __init__(
        self,
        *,
        cpu_bytes: int = 0,
        disk_path: Optional[str] = None,
        disk_bytes: int = 0,
        remote_url: Optional[str] = None,
        on_local_drop: Optional[Callable[[str], None]] = None,
    ):
        self.cpu = CPUTier(cpu_bytes) if cpu_bytes > 0 else None
        self.disk = (
            DiskTier(disk_path, disk_bytes) if disk_path and disk_bytes > 0 else None
        )
        self.remote = RemoteTier(remote_url) if remote_url else None
        self.on_local_drop = on_local_drop
        self._lock = threading.RLock()
        # bumped from every thread that reads the store (engine device
        # thread, transfer receiver, proactive spill) — shared `+=` on a
        # dict slot loses increments without the lock (graftcheck GC004)
        self.hits = {"cpu": 0, "disk": 0, "remote": 0}  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        # blobs evicted out the BOTTOM of the local hierarchy (disk-tier
        # eviction, or CPU-tier eviction with no disk tier). Without a remote
        # tier this is permanent KV loss — it used to happen silently;
        # exported as kv_offload_dropped_evictions_total on /metrics
        self.dropped_evictions = 0
        # blobs that failed their checksum/version check on read and were
        # quarantined (vllm:kv_corrupt_pages_total); nonzero means a tier is
        # flipping bits or a rolling upgrade crossed an incompatible format
        self.corrupt_pages = 0

    def enabled(self) -> bool:
        # NB: explicit None checks — the tiers define __len__, so an *empty*
        # tier is falsy and `bool(self.cpu)` would wrongly disable the store.
        return (
            self.cpu is not None or self.disk is not None or self.remote is not None
        )

    def durable(self) -> bool:
        """True if some tier survives process death (disk or remote) — the
        prerequisite for warm-start state to mean anything across restarts."""
        return self.disk is not None or self.remote is not None

    def _spill(self, evicted: list[tuple[str, bytes]]) -> None:
        for k, b in evicted:
            if self.disk is not None:
                for dropped in self.disk.put(k, b):
                    self._dropped_locally(dropped)
            else:
                self._dropped_locally(k)

    def _dropped_locally(self, key: str) -> None:
        self.dropped_evictions += 1
        if self.on_local_drop is not None and not self.contains_local(key):
            self.on_local_drop(key)

    def put_local(self, key: str, blob: bytes) -> None:
        """Insert into the local tiers only (no remote write-through) — used
        for chunks *received* from a peer, which already live remotely."""
        with self._lock:
            if self.cpu is not None:
                self._spill(self.cpu.put(key, blob))
            elif self.disk is not None:
                for dropped in self.disk.put(key, blob):
                    self._dropped_locally(dropped)

    def put(self, key: str, blob: bytes) -> None:
        self.put_local(key, blob)
        if self.remote is not None:
            self.remote.put(key, blob)

    def _verified(self, key: str, blob: bytes, tier_name: str, tier) -> bool:
        """True if ``blob`` passes its integrity check; on failure the entry
        is quarantined (deleted from the tier that served it) and counted so
        the get-walk falls through to the next tier / recompute."""
        try:
            verify_blob(blob)
            return True
        except KVIntegrityError as e:
            self.corrupt_pages += 1
            logger.warning(
                "quarantining corrupt kv blob %s from %s tier: %s",
                key, tier_name, e,
            )
            try:
                tier.delete(key)
            except Exception:  # noqa: BLE001 - quarantine is best-effort
                pass
            return False

    def get_authoritative(self, key: str) -> Optional[bytes]:
        """Read a MUTABLE key (warm-start head pointer), preferring SHARED
        sources over this process's private caches: remote first, then the
        disk FILE (bypassing this process's in-memory index — another
        incarnation sharing the directory may have written it after our
        index was built), DRAM last. The ordinary ``get`` walk is designed
        for immutable content-addressed blobs, where a local copy is as good
        as any; for a mutable key it would return our own stale copy and,
        e.g., blind an old engine incarnation to the newer generation that
        fenced it."""
        if self.remote is not None:
            blob = self.remote.get(key)
            if blob is not None and self._verified(key, blob, "remote", self.remote):
                return blob
        with self._lock:
            if self.disk is not None:
                blob = self.disk.get_fresh(key)
                if blob is not None and self._verified(key, blob, "disk", self.disk):
                    return blob
            if self.cpu is not None:
                blob = self.cpu.get(key)
                if blob is not None and self._verified(key, blob, "cpu", self.cpu):
                    return blob
        return None

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            if self.cpu is not None:
                blob = self.cpu.get(key)
                if blob is not None and self._verified(key, blob, "cpu", self.cpu):
                    self.hits["cpu"] += 1
                    return blob
            if self.disk is not None:
                blob = self.disk.get(key)
                if blob is not None and self._verified(key, blob, "disk", self.disk):
                    self.hits["disk"] += 1
                    if self.cpu is not None:  # promote
                        self._spill(self.cpu.put(key, blob))
                    return blob
        if self.remote is not None:
            blob = self.remote.get(key)
            if blob is not None and self._verified(key, blob, "remote", self.remote):
                with self._lock:
                    # counter bump inside the promote's lock window: the
                    # unlocked `+=` raced the cpu/disk paths' locked bumps
                    # and dropped increments (found by graftcheck GC004)
                    self.hits["remote"] += 1
                    if self.cpu is not None:
                        self._spill(self.cpu.put(key, blob))
                return blob
        self.misses += 1
        return None

    def persist(self, key: str, force: bool = False) -> bool:
        """Ensure a DRAM-tier blob also has a process-death-durable local
        copy: copy it to the disk tier if one exists (remote copies are
        already written through by ``put``). Warm-start state must outlive
        the process — a cpu+disk hierarchy otherwise holds the newest (last
        to evict) blobs only in DRAM. ``force`` re-copies even when the key
        is already on disk: content-addressed page blobs are immutable (skip
        is safe and cheap), but MUTABLE keys (the warm-start head pointer)
        would otherwise keep a stale durable copy forever. Returns True if a
        durable local copy exists afterwards."""
        with self._lock:
            if self.disk is None:
                return False
            if not force and key in self.disk:
                return True
            blob = self.cpu.get(key) if self.cpu is not None else None
            if blob is None:
                return key in self.disk
            for dropped in self.disk.put(key, blob):
                self._dropped_locally(dropped)
            return key in self.disk

    def contains_local(self, key: str) -> bool:
        with self._lock:
            return bool(
                (self.cpu is not None and key in self.cpu)
                or (self.disk is not None and key in self.disk)
            )

    def contains(self, key: str) -> bool:
        if self.contains_local(key):
            return True
        return self.remote is not None and key in self.remote

    def stats(self) -> dict:
        with self._lock:
            return {
                "cpu_entries": len(self.cpu) if self.cpu else 0,
                "cpu_bytes": self.cpu.used_bytes if self.cpu else 0,
                "disk_entries": len(self.disk) if self.disk else 0,
                "disk_bytes": self.disk.used_bytes if self.disk else 0,
                "hits": dict(self.hits),
                "misses": self.misses,
                "dropped_evictions": self.dropped_evictions,
                "corrupt_pages": self.corrupt_pages,
                "remote_errors": self.remote.errors if self.remote else 0,
            }
