"""Tiered KV blob store: host-DRAM -> local disk -> remote cache server.

The LMCache-equivalent storage hierarchy the reference configures per engine
(/root/reference helm/templates/deployment-vllm-multi.yaml:297-314:
`LMCACHE_MAX_LOCAL_CPU_SIZE`, `LMCACHE_MAX_LOCAL_DISK_SIZE` + path,
`LMCACHE_REMOTE_URL` + serde). Keys are chunk-hash hex strings (the same
rolling hashes as engine/kv_manager.py and the router trie); values are
serde blobs.

Policy: ``put`` writes to DRAM (and through to the remote tier so other
instances can share); DRAM eviction spills to disk; disk eviction drops the
blob locally. ``get`` walks DRAM -> disk -> remote and promotes hits to DRAM.
Evictions that remove the *last local* copy surface through ``on_local_drop``
so the engine can tell the KV-index controller.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

from production_stack_tpu.kvoffload.protocol import BlockingClient, parse_hostport
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class CPUTier:
    """Byte-capped LRU in host DRAM."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._data: OrderedDict[str, bytes] = OrderedDict()
        self.used_bytes = 0

    def get(self, key: str) -> Optional[bytes]:
        blob = self._data.get(key)
        if blob is not None:
            self._data.move_to_end(key)
        return blob

    def put(self, key: str, blob: bytes) -> list[tuple[str, bytes]]:
        """Insert; returns evicted (key, blob) pairs for spill-down."""
        if len(blob) > self.max_bytes:
            return [(key, blob)]
        if key in self._data:
            self.used_bytes -= len(self._data[key])
            del self._data[key]
        self._data[key] = blob
        self.used_bytes += len(blob)
        evicted = []
        while self.used_bytes > self.max_bytes:
            k, b = self._data.popitem(last=False)
            self.used_bytes -= len(b)
            evicted.append((k, b))
        return evicted

    def delete(self, key: str) -> None:
        blob = self._data.pop(key, None)
        if blob is not None:
            self.used_bytes -= len(blob)

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)


class DiskTier:
    """Byte-capped LRU of blob files in a directory."""

    def __init__(self, path: str, max_bytes: int):
        self.path = path
        self.max_bytes = max_bytes
        os.makedirs(path, exist_ok=True)
        self._index: OrderedDict[str, int] = OrderedDict()  # key -> size
        self.used_bytes = 0
        for name in sorted(os.listdir(path)):  # recover after restart
            if name.endswith(".kv"):
                size = os.path.getsize(os.path.join(path, name))
                self._index[name[:-3]] = size
                self.used_bytes += size

    def _file(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.kv")

    def get(self, key: str) -> Optional[bytes]:
        if key not in self._index:
            return None
        try:
            with open(self._file(key), "rb") as f:
                blob = f.read()
        except OSError:
            self.delete(key)
            return None
        self._index.move_to_end(key)
        return blob

    def put(self, key: str, blob: bytes) -> list[str]:
        """Write; returns keys evicted (dropped entirely)."""
        if len(blob) > self.max_bytes:
            return [key]
        self.delete(key)
        tmp = self._file(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self._file(key))
        self._index[key] = len(blob)
        self.used_bytes += len(blob)
        dropped = []
        while self.used_bytes > self.max_bytes:
            k, size = self._index.popitem(last=False)
            self.used_bytes -= size
            try:
                os.unlink(self._file(k))
            except OSError:
                pass
            dropped.append(k)
        return dropped

    def delete(self, key: str) -> None:
        size = self._index.pop(key, None)
        if size is not None:
            self.used_bytes -= size
            try:
                os.unlink(self._file(key))
            except OSError:
                pass

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def __len__(self) -> int:
        return len(self._index)


class RemoteTier:
    """Client view of the shared cache server (kvoffload/cache_server.py)."""

    def __init__(self, url: str, timeout: float = 10.0):
        host, port = parse_hostport(url, default_port=8200)
        self._client = BlockingClient(host, port, timeout=timeout)
        self._lock = threading.Lock()
        self.errors = 0

    def _request(self, header: dict, payload: bytes = b"") -> tuple[dict, bytes]:
        with self._lock:
            return self._client.request(header, payload)

    def get(self, key: str) -> Optional[bytes]:
        try:
            hdr, body = self._request({"op": "get", "key": key})
            return body if hdr.get("ok") and hdr.get("found") else None
        except Exception as e:
            self.errors += 1
            logger.warning("remote kv get failed: %s", e)
            return None

    def put(self, key: str, blob: bytes) -> None:
        try:
            self._request({"op": "put", "key": key}, blob)
        except Exception as e:
            self.errors += 1
            logger.warning("remote kv put failed: %s", e)

    def __contains__(self, key: str) -> bool:
        try:
            hdr, _ = self._request({"op": "exists", "key": key})
            return bool(hdr.get("found"))
        except Exception:
            self.errors += 1
            return False

    def close(self) -> None:
        self._client.close()


class TieredKVStore:
    """The per-engine offload hierarchy. Thread-safe for the engine loop +
    background reporters."""

    def __init__(
        self,
        *,
        cpu_bytes: int = 0,
        disk_path: Optional[str] = None,
        disk_bytes: int = 0,
        remote_url: Optional[str] = None,
        on_local_drop: Optional[Callable[[str], None]] = None,
    ):
        self.cpu = CPUTier(cpu_bytes) if cpu_bytes > 0 else None
        self.disk = (
            DiskTier(disk_path, disk_bytes) if disk_path and disk_bytes > 0 else None
        )
        self.remote = RemoteTier(remote_url) if remote_url else None
        self.on_local_drop = on_local_drop
        self._lock = threading.RLock()
        self.hits = {"cpu": 0, "disk": 0, "remote": 0}
        self.misses = 0
        # blobs evicted out the BOTTOM of the local hierarchy (disk-tier
        # eviction, or CPU-tier eviction with no disk tier). Without a remote
        # tier this is permanent KV loss — it used to happen silently;
        # exported as kv_offload_dropped_evictions_total on /metrics
        self.dropped_evictions = 0

    def enabled(self) -> bool:
        # NB: explicit None checks — the tiers define __len__, so an *empty*
        # tier is falsy and `bool(self.cpu)` would wrongly disable the store.
        return (
            self.cpu is not None or self.disk is not None or self.remote is not None
        )

    def _spill(self, evicted: list[tuple[str, bytes]]) -> None:
        for k, b in evicted:
            if self.disk is not None:
                for dropped in self.disk.put(k, b):
                    self._dropped_locally(dropped)
            else:
                self._dropped_locally(k)

    def _dropped_locally(self, key: str) -> None:
        self.dropped_evictions += 1
        if self.on_local_drop is not None and not self.contains_local(key):
            self.on_local_drop(key)

    def put_local(self, key: str, blob: bytes) -> None:
        """Insert into the local tiers only (no remote write-through) — used
        for chunks *received* from a peer, which already live remotely."""
        with self._lock:
            if self.cpu is not None:
                self._spill(self.cpu.put(key, blob))
            elif self.disk is not None:
                for dropped in self.disk.put(key, blob):
                    self._dropped_locally(dropped)

    def put(self, key: str, blob: bytes) -> None:
        self.put_local(key, blob)
        if self.remote is not None:
            self.remote.put(key, blob)

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            if self.cpu is not None:
                blob = self.cpu.get(key)
                if blob is not None:
                    self.hits["cpu"] += 1
                    return blob
            if self.disk is not None:
                blob = self.disk.get(key)
                if blob is not None:
                    self.hits["disk"] += 1
                    if self.cpu is not None:  # promote
                        self._spill(self.cpu.put(key, blob))
                    return blob
        if self.remote is not None:
            blob = self.remote.get(key)
            if blob is not None:
                self.hits["remote"] += 1
                with self._lock:
                    if self.cpu is not None:
                        self._spill(self.cpu.put(key, blob))
                return blob
        self.misses += 1
        return None

    def contains_local(self, key: str) -> bool:
        with self._lock:
            return bool(
                (self.cpu is not None and key in self.cpu)
                or (self.disk is not None and key in self.disk)
            )

    def contains(self, key: str) -> bool:
        if self.contains_local(key):
            return True
        return self.remote is not None and key in self.remote

    def stats(self) -> dict:
        with self._lock:
            return {
                "cpu_entries": len(self.cpu) if self.cpu else 0,
                "cpu_bytes": self.cpu.used_bytes if self.cpu else 0,
                "disk_entries": len(self.disk) if self.disk else 0,
                "disk_bytes": self.disk.used_bytes if self.disk else 0,
                "hits": dict(self.hits),
                "misses": self.misses,
                "dropped_evictions": self.dropped_evictions,
                "remote_errors": self.remote.errors if self.remote else 0,
            }
