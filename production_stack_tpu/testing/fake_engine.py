"""Fake TPU engine: an OpenAI-API mock for router/stack testing with zero
accelerators — the keystone test fixture.

Parity: src/tests/perftest/fake-openai-server.py:1-170 in /root/reference
(streams tokens at --speed with injectable --ttft, tracks running requests),
extended with /metrics in the engine's vllm:* format, sleep/wake, and optional
kv-transfer query params so disaggregated-prefill flows are testable.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid

from aiohttp import web

from production_stack_tpu.tracing import (
    decode_step_time_hist,
    export_for_query,
    get_collector,
    prefill_time_hist,
    queue_time_hist,
    render_phase_histograms,
)

STATE = {
    "running": 0,
    "total": 0,
    "sleeping": False,
}


def make_app(model: str, speed: float, ttft: float, model_label: str | None = None):
    async def health(request):
        return web.Response(text="")

    async def models(request):
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": model,
                        "object": "model",
                        "created": int(time.time()),
                        "owned_by": "fake-engine",
                    }
                ],
            }
        )

    async def metrics(request):
        text = (
            f'vllm:num_requests_running{{model_name="{model}"}} {STATE["running"]}\n'
            f'vllm:num_requests_waiting{{model_name="{model}"}} 0\n'
            f'vllm:gpu_cache_usage_perc{{model_name="{model}"}} 0.42\n'
            f'vllm:gpu_prefix_cache_hits_total{{model_name="{model}"}} 10\n'
            f'vllm:gpu_prefix_cache_queries_total{{model_name="{model}"}} 20\n'
        )
        # per-phase histograms, same names as the real engine's /metrics so
        # smoke tests and dashboard queries exercise the fake identically
        text += "\n".join(render_phase_histograms(f'model_name="{model}"')) + "\n"
        return web.Response(text=text, content_type="text/plain")

    async def traces(request):
        payload, status = export_for_query(request.query)
        return web.json_response(payload, status=status)

    async def completions(request):
        return await _generate(request, chat=False)

    async def chat(request):
        return await _generate(request, chat=True)

    async def _generate(request, chat: bool):
        if STATE["sleeping"]:
            return web.json_response({"error": "sleeping"}, status=503)
        body = await request.json()
        max_tokens = int(body.get("max_tokens", 16))
        stream = bool(body.get("stream", False))
        req_id = request.headers.get("X-Request-Id", uuid.uuid4().hex)
        uid = request.headers.get("x-user-id")
        if uid:
            # visible marker for tests asserting user-id header propagation
            print(f"x-user-id={uid}", flush=True)
        # distributed tracing, same span model as the real engine
        # (engine.request > queue/prefill/decode) so router e2e tests can
        # assert full-stack trace propagation without a TPU
        collector = get_collector()
        trace_ctx = collector.root_from_headers(request.headers).child()
        t_accept = time.time()
        STATE["running"] += 1
        STATE["total"] += 1
        created = int(time.time())
        oid = ("chatcmpl-" if chat else "cmpl-") + req_id

        def _phase(name, start, dur, **attrs):
            collector.record(
                name, trace_ctx.child(), start, dur,
                seq_id=req_id, **attrs,
            )

        def _decode_done(t_first):
            t_done = time.time()
            _phase("engine.decode", t_first, t_done - t_first,
                   output_tokens=max_tokens, finish_reason="length")
            if max_tokens > 1:
                decode_step_time_hist.observe(
                    (t_done - t_first) / (max_tokens - 1)
                )

        try:
            t_q = time.time()
            _phase("engine.queue", t_accept, t_q - t_accept)
            queue_time_hist.observe(t_q - t_accept)
            await asyncio.sleep(ttft)  # injected prefill time
            t_first = time.time()
            _phase("engine.prefill", t_q, t_first - t_q, prompt_tokens=10)
            prefill_time_hist.observe(t_first - t_q)
            if not stream:
                await asyncio.sleep(max_tokens / speed)
                _decode_done(t_first)
                text = "Hello " * max_tokens
                choice = (
                    {"index": 0, "message": {"role": "assistant", "content": text},
                     "finish_reason": "length"}
                    if chat
                    else {"index": 0, "text": text, "finish_reason": "length"}
                )
                return web.json_response(
                    {
                        "id": oid, "object": "chat.completion" if chat else "text_completion",
                        "created": created, "model": model, "choices": [choice],
                        "usage": {
                            "prompt_tokens": 10, "completion_tokens": max_tokens,
                            "total_tokens": 10 + max_tokens,
                        },
                    },
                    headers={"X-Request-Id": req_id},
                )
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream", "X-Request-Id": req_id}
            )
            await resp.prepare(request)
            for i in range(max_tokens):
                delta = {"content": "Hello "} if chat else None
                choice = (
                    {"index": 0, "delta": delta, "finish_reason": None}
                    if chat
                    else {"index": 0, "text": "Hello ", "finish_reason": None}
                )
                await resp.write(
                    f"data: {json.dumps({'id': oid, 'object': 'chat.completion.chunk' if chat else 'text_completion', 'created': created, 'model': model, 'choices': [choice]})}\n\n".encode()
                )
                await asyncio.sleep(1.0 / speed)
            _decode_done(t_first)
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        finally:
            STATE["running"] -= 1
            collector.record(
                "engine.request", trace_ctx, t_accept,
                time.time() - t_accept, request_id=req_id, model=model,
            )

    async def sleep(request):
        STATE["sleeping"] = True
        return web.Response(text="")

    async def wake_up(request):
        STATE["sleeping"] = False
        return web.Response(text="")

    async def is_sleeping(request):
        return web.json_response({"is_sleeping": STATE["sleeping"]})

    async def tokenize(request):
        body = await request.json()
        text = body.get("prompt", "")
        return web.json_response(
            {"tokens": list(text.encode()), "count": len(text.encode()), "max_model_len": 4096}
        )

    app = web.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/v1/traces", traces)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/sleep", sleep)
    app.router.add_post("/wake_up", wake_up)
    app.router.add_get("/is_sleeping", is_sleeping)
    app.router.add_post("/tokenize", tokenize)
    return app


def main():
    p = argparse.ArgumentParser("fake-tpu-engine")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--model", default="fake/model")
    p.add_argument("--speed", type=float, default=100.0, help="tokens per second")
    p.add_argument("--ttft", type=float, default=0.0, help="injected TTFT seconds")
    p.add_argument("--model-label", default=None)
    args = p.parse_args()
    web.run_app(
        make_app(args.model, args.speed, args.ttft, args.model_label),
        port=args.port, print=None,
    )


if __name__ == "__main__":
    main()
