"""Fake TPU engine: an OpenAI-API mock for router/stack testing with zero
accelerators — the keystone test fixture.

Parity: src/tests/perftest/fake-openai-server.py:1-170 in /root/reference
(streams tokens at --speed with injectable --ttft, tracks running requests),
extended with /metrics in the engine's vllm:* format, sleep/wake, optional
kv-transfer query params so disaggregated-prefill flows are testable, and
fault injection for the router's failure-domain layer (tests/test_chaos.py,
scripts/chaos_check.py):

- ``--fail-rate P``      each generation request 500s with probability P
- ``--fail-first-n N``   the first N generation requests 500, then recover
- ``--fail-after-chunks N``  streams N chunks then drops the connection
                         (mid-stream truncation)
- ``--hang``             accepts the request, never sends headers (hung
                         engine; only an abort or a router deadline frees it)
- ``--hang-after-chunks N``  streams N chunks then stalls forever
- ``--saturate-after-n N``  engine admission control: a generation request
                         arriving while N are already in flight is SHED
                         with 429 + Retry-After (bounded queue depth — the
                         in-flight count provably never exceeds N)
- ``--shed-rate P``      each generation request 429s (with Retry-After)
                         with probability P
- ``--retry-after S``    Retry-After seconds advertised on shed responses
- ``--crash-after-n N``  HARD crash: once N generation requests have been
                         accepted, the process ``os._exit``s abruptly —
                         mid-stream when streaming, before responding
                         otherwise. No drain, no manifest spill: models the
                         kill -9 / OOM half of restart chaos (SIGTERM models
                         the graceful half)
- ``--restart-restore-pages M``  models a WARM restart: /metrics advertises
                         ``vllm:warm_start_restored_pages M`` (+ manifest
                         age), so rolling-restart chaos runs can assert the
                         warm-start surface without a real engine
- ``--slo-itl-ms X``     the synthetic SLO terminal records report X as
                         their inter-token p99 (``GET /slo_records``, same
                         wire shape as the real engine) — set above the
                         router's objective to drive its violation counters
- ``--compile-stall-ms X``  the first generation stalls X ms and records a
                         flight-recorder ``compile`` event (cold-XLA model)
- ``--kv-directory-url``  fleet-wide KV directory emulation (ISSUE 9): the
                         fake registers with the cache server's directory
                         and, on every COMPLETED generation, publishes the
                         prompt's chunk hashes as resident claims. Hashes
                         are the real chain (engine/kv_manager.prefix_hashes
                         over ByteTokenizer tokens, page 16) — deterministic
                         per prompt and identical to what the router's
                         kvaware-v2 lookup computes, so router e2e/chaos
                         tests exercise resident ranking with zero TPUs.
                         Generation = boot-time ms (monotonic across
                         restarts), so a reborn fake fences its old claims.
- ``--flight-dump-dir D``  arm flight-recorder anomaly dumps (SIGTERM
                         drain, shed bursts) into D; the synthetic
                         sched/kv/shed event feed matches the real engine's
- ``POST /abort``        cancels an in-flight request by X-Request-Id, like
                         the real engine's abort endpoint
- ``--migration``        live sequence migration (ISSUE 10, docs/migration.md)
                         in the REAL wire shapes: ``POST /migrate_out``
                         freezes a streaming request at a deterministic chunk
                         boundary, ships a sealed ``SequenceSnapshot``
                         (production_stack_tpu/migration/state.py — the same
                         document a real engine ships) to the target's
                         ``POST /migrate_in``, and on acceptance ends the
                         source stream with the ``pstpu_migration`` control
                         event the router splices on; the target parks the
                         continuation and serves it via
                         ``POST /migrate_attach`` (same chunk/usage/[DONE]
                         shapes as the real engine), so router splice e2e and
                         the scale-cycle chaos scenario run without TPUs.
                         ``GET /migratable`` lists live streams for the fleet
                         controller. GC005 endpoint parity holds: the real
                         engine serves the same four routes.
- ``--warm-prefetch-on-boot N``  scale-up warm-up modelling: at startup pull
                         the directory's top-N fleet-warm chunk hashes
                         (``dir_top_prefixes``) and count a warm prefix hit
                         for every later request whose prompt chain starts in
                         that set.
- ``--fabric``           peer-to-peer KV fabric emulation (docs/kv-fabric.md)
                         in the REAL wire shapes: an asyncio TCP listener
                         speaking the four fabric ops (``fabric_hello`` /
                         ``fabric_probe`` / ``fabric_pull`` / ``fabric_push``)
                         with versioned CRC-framed ``kvfabric.wire`` frames
                         of deterministic synthetic pages, advertised on
                         ``GET /kv_fabric`` like the real engine. With
                         ``--kv-directory-url`` each generation first looks
                         its prompt chain up in the directory and PULLS
                         missing pages from the resident owner's fabric
                         (generation-fenced), so cross-engine resident pulls
                         and their tier fallback are chaos-testable sans TPU.
- ``--fabric-fail-rate P``  each fabric op replies with an error with
                         probability P (peers count fallbacks)
- ``--fabric-hang``      fabric ops stall forever (peer deadlines + breaker)
- ``POST /fabric_down``  chaos hook: close the fabric listener mid-load
                         (the fabric-outage scenario's victim switch) while
                         the HTTP plane keeps serving

Observability used by chaos assertions: ``fake:running_peak`` (bounded-queue
proof), ``fake:served_total`` (generation requests accepted by THIS process —
resets on restart, which is how a chaos run detects traffic returning to a
reborn backend), ``fake:completed_total`` (generations that ran to the end —
fleet-wide sum proves an idempotent replay executed exactly once),
``fake:abort_requests_total`` (router-initiated reclaims received),
``fake:migrations_out_total`` / ``fake:migrations_in_total`` (live streams
moved out of / resumed on this process), ``fake:warm_prefetch_chunks``
(fleet-warm chunks pulled at boot), ``fake:warm_prefix_hits_total``
(requests whose prompt chain hit the prefetched set), and the per-SLO-class
split ``fake:served_by_class_total`` / ``fake:shed_by_class_total``
(priority label, docs/failure-handling.md — the mixed-class-overload chaos
scenario asserts every shed landed on batch).

SIGTERM drains like the real engine (api_server graceful drain): /health
flips to 503, new generation requests are refused, in-flight streams finish.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import signal
import time
import uuid

from aiohttp import web

import collections

from production_stack_tpu.tracing import (
    configure_flightrecorder,
    decode_step_time_hist,
    export_for_query,
    flightrecorder,
    get_collector,
    get_flightrecorder,
    prefill_time_hist,
    queue_time_hist,
    render_collector_metrics,
    render_flightrecorder_metrics,
    render_phase_histograms,
)

# the fake is a pure-asyncio process: every handler, fault timer, and
# publisher task mutates this on the loop (GC007 guards the convention)
STATE = {  # owned-by: event-loop
    "running": 0,
    "running_peak": 0,      # high-watermark of concurrent in-flight requests
    "total": 0,
    "sleeping": False,
    "draining": False,
    "served": 0,            # generation requests seen (drives --fail-first-n)
    "completed": 0,         # generations that ran to the end (replay dedupe)
    "aborts": 0,            # POST /abort calls received (router reclaims)
    "shed": 0,              # 429s emitted (saturate-after-n / shed-rate)
    # per-SLO-class accounting (docs/failure-handling.md priority classes):
    # chaos mixed-class-overload asserts every shed lands on batch until the
    # interactive reserve is exhausted, through these counters
    "served_by_class": {"interactive": 0, "batch": 0},
    "shed_by_class": {"interactive": 0, "batch": 0},
    # rolling interactive-class latency windows backing the fake's
    # vllm:interactive_{ttft,itl}_p99_ms gauges (same names as the real
    # engine so the fleet controller's latency_protect scrapes identically)
    "interactive_ttft_ms": collections.deque(maxlen=64),
    "interactive_itl_ms": collections.deque(maxlen=64),
    "inflight": {},         # req_id -> handler asyncio.Task (for /abort)
    # per-request SLO terminal records (same wire shape as the real engine's
    # GET /slo_records) so router-side SLO aggregation is testable sans TPU
    "slo_seq": 0,
    "slo_records": collections.deque(maxlen=2048),
    # shed timestamps feeding the flight recorder's shed-burst anomaly dump
    "shed_times": collections.deque(maxlen=64),
    "compile_stalled": False,  # --compile-stall-ms fires once, on request 1
    # live migration (--migration; all event-loop-owned)
    "migrations_out": 0,    # streams frozen + shipped off this process
    "migrations_in": 0,     # snapshots accepted + parked here
    "migrating": {},        # req_id -> freeze/ship coordination entry
    "parked": {},           # req_id -> {"snap", "remaining", "t"}
    "streams": set(),       # req_ids currently streaming (migratable set)
    "progress": {},         # req_id -> output tokens emitted so far
    "meta": {},             # req_id -> presentation meta (snapshot source)
    # scale-up warm-up modelling (--warm-prefetch-on-boot)
    "prefetched": set(),    # dir_top_prefixes hashes pulled at boot
    "warm_prefix_hits": 0,  # requests whose prompt chain hit that set
    # KV fabric emulation (--fabric; docs/kv-fabric.md, all event-loop-owned)
    "fabric_pulled": 0,     # pages pulled from peer fakes over the fabric
    "fabric_served": 0,     # pages this fake's listener served to peers
    "fabric_received": 0,   # pages landed here via fabric_push
    "fabric_fallbacks": 0,  # fabric fetches that failed over to the tier path
    "fabric_resident": set(),  # key hexes "resident" on this fake
    "fabric_down": False,   # POST /fabric_down chaos hook fired
}


def _push_slo_record(model: str, req_id: str, outcome: str, *,
                     ttft_ms=None, itl_p99_ms=None, output_tokens=0,
                     queue_ms=0.0, e2e_ms=None, trace_id=None,
                     priority: str = "interactive") -> None:
    """Synthetic terminal record, same fields the real engine attributes
    (engine.LLMEngine._record_slo) so the router's scraper cannot tell the
    difference."""
    STATE["slo_seq"] += 1
    # mirrored into the flight recorder too, like the real engine's
    # _record_slo — anomaly dumps carry the requests that were in flight
    get_flightrecorder().record(
        "slo", step=STATE["slo_seq"], trace_id=trace_id,
        request_id=req_id, outcome=outcome, ttft_ms=ttft_ms,
        itl_p99_ms=itl_p99_ms, output_tokens=output_tokens,
    )
    STATE["slo_records"].append({
        "seq": STATE["slo_seq"],
        "request_id": req_id,
        "model": model,
        "outcome": outcome,
        "finish_reason": "length" if outcome == "ok" else outcome,
        "queue_ms": round(queue_ms, 3),
        "ttft_ms": None if ttft_ms is None else round(ttft_ms, 3),
        "e2e_ms": None if e2e_ms is None else round(e2e_ms, 3),
        "prompt_tokens": 10,
        "output_tokens": output_tokens,
        "cached_tokens": 0,
        "itl_p99_ms": None if itl_p99_ms is None else round(itl_p99_ms, 3),
        "kv_pages_peak": max(1, output_tokens // 16 + 1),
        "priority": priority,
        "trace_id": trace_id,
        "t": time.time(),
    })


class _FakeDirectoryPublisher:
    """Minimal asyncio publisher for --kv-directory-url: one persistent frame
    connection, register-then-publish, reconnect-on-error. Publishes the
    REAL chunk-hash chain (ByteTokenizer tokens, page 16) so the directory's
    token lookups — fed by the router's own ByteTokenizer — match exactly."""

    PAGE = 16

    def __init__(self, directory_url: str, engine_url: str):
        from production_stack_tpu.kvoffload.protocol import parse_hostport

        self.host, self.port = parse_hostport(directory_url, default_port=8200)
        self.engine_url = engine_url
        # boot epoch in ms: strictly higher on every rebirth, so the
        # directory fences the previous incarnation's claims (ISSUE 9)
        self.generation = int(time.time() * 1000)
        self._reader = self._writer = None
        self._lock = asyncio.Lock()
        self.published = 0

    async def _request(self, header: dict, payload: bytes = b"") -> dict:
        from production_stack_tpu.kvoffload.protocol import (
            read_frame,
            write_frame,
        )

        async with self._lock:
            try:
                if self._writer is None:
                    self._reader, self._writer = await asyncio.wait_for(
                        asyncio.open_connection(self.host, self.port), 5.0
                    )
                    await write_frame(self._writer, {
                        "op": "dir_register", "url": self.engine_url,
                        "page_size": self.PAGE,
                        "generation": self.generation,
                    })
                    await asyncio.wait_for(read_frame(self._reader), 5.0)
                await write_frame(self._writer, header, payload)
                hdr, _ = await asyncio.wait_for(read_frame(self._reader), 5.0)
                return hdr
            except Exception:
                if self._writer is not None:
                    try:
                        self._writer.close()
                    except Exception:
                        pass
                self._reader = self._writer = None
                raise

    async def register(self) -> None:
        try:
            await self._request({"op": "ping"})  # opens + registers
        except Exception as e:  # noqa: BLE001 - directory may not be up yet
            print(f"fake-engine: directory register failed: {e}", flush=True)

    async def publish_prompt(self, prompt: str) -> None:
        """Deterministic claim publish on stream completion: resident (HBM)
        claims plus SHARED claims backed by tiny sealed blobs put into the
        co-hosted cache server — the directory verifies shared claims
        against the actual blob map at lookup time (blob_check), so shared
        visibility (restorable ranking, dir_top_prefixes warm-up) is only
        testable when the blobs really exist."""
        from production_stack_tpu.engine.kv_manager import prefix_hashes
        from production_stack_tpu.engine.tokenizer import ByteTokenizer
        from production_stack_tpu.kvoffload.serde import seal_bytes

        tokens = ByteTokenizer().encode(prompt)
        hashes = prefix_hashes(tokens, self.PAGE)
        if not hashes:
            return
        entries = [[h.hex(), d, 1.0] for d, h in enumerate(hashes)]
        try:
            await self._request({
                "op": "dir_publish", "url": self.engine_url,
                "generation": self.generation, "tier": "hbm",
                "page_size": self.PAGE, "entries": entries,
            })
            for h, _d, _s in entries:
                await self._request(
                    {"op": "put", "key": h},
                    seal_bytes(b"fake-kv", kind="page"),
                )
            await self._request({
                "op": "dir_publish", "url": self.engine_url,
                "generation": self.generation, "tier": "shared",
                "page_size": self.PAGE, "entries": entries,
            })
            self.published += len(hashes)
        except Exception as e:  # noqa: BLE001 - the directory is a hint
            print(f"fake-engine: directory publish failed: {e}", flush=True)

    async def top_prefixes(self, limit: int) -> list:
        """Scale-up warm-up: the fleet's warmest chunk hashes, heads-first
        (the same ``dir_top_prefixes`` op a real engine's
        --warm-prefetch-on-boot pulls)."""
        hdr = await self._request({
            "op": "dir_top_prefixes", "limit": limit, "page_size": self.PAGE,
        })
        return hdr.get("hashes") or []


def _prompt_text(body: dict, chat: bool) -> str:
    """Same prompt extraction as the router's PrefixAwareRouter._prompt_of,
    so the fake's published hashes align with the router's lookups."""
    if "prompt" in body:
        p = body["prompt"]
        return p if isinstance(p, str) else (p[0] if p else "")
    return "".join(str(m.get("content", "")) for m in body.get("messages", []) or [])


def make_app(model: str, speed: float, ttft: float, model_label: str | None = None,
             faults: dict | None = None):
    faults = faults or {}
    fail_rate = float(faults.get("fail_rate", 0.0))
    fail_first_n = int(faults.get("fail_first_n", 0))
    fail_after_chunks = faults.get("fail_after_chunks")
    hang = bool(faults.get("hang", False))
    hang_after_chunks = faults.get("hang_after_chunks")
    saturate_after_n = faults.get("saturate_after_n")
    # advertised serving-mesh tp degree (--tensor-parallel): chaos scenarios
    # run fleets of mixed-shape fakes to prove router scraping, migration,
    # and warm-start round-trip the sharded-engine advert unchanged
    tensor_parallel = int(faults.get("tensor_parallel") or 1)
    shed_rate = float(faults.get("shed_rate", 0.0))
    retry_after = f"{float(faults.get('retry_after') or 1):g}"
    crash_after_n = faults.get("crash_after_n")
    restore_pages = int(faults.get("restart_restore_pages") or 0)
    # synthetic observability feed (ISSUE 7): --slo-itl-ms sets the ITL p99
    # the terminal records report (drives router-side SLO violation paths);
    # --compile-stall-ms injects one compile stall + flight-recorder compile
    # event; --flight-dump-dir arms anomaly dumps (SIGTERM / shed burst)
    slo_itl_ms = faults.get("slo_itl_ms")
    # class-aware admission (docs/failure-handling.md priority classes):
    # batch sheds --interactive-reserve slots EARLIER than interactive, so
    # the last slots under saturate-after-n stay reserved for interactive
    interactive_reserve = int(faults.get("interactive_reserve") or 0)
    # --interactive-slo-degrade-ms: inflate every interactive request's
    # reported TTFT/ITL by this much — models an engine failing its
    # interactive SLO, driving the controller's latency_protect policy and
    # the router's batch-avoidance filter without real latency injection
    interactive_slo_degrade_ms = float(
        faults.get("interactive_slo_degrade_ms") or 0.0
    )
    compile_stall_ms = float(faults.get("compile_stall_ms") or 0.0)
    flight_dump_dir = faults.get("flight_dump_dir")
    if flight_dump_dir:
        configure_flightrecorder(dump_dir=flight_dump_dir)
    start_time = time.time()
    # fleet-wide KV directory emulation (ISSUE 9): register + deterministic
    # publish on stream completion, so router-v2 e2e runs without a TPU
    dirpub = None
    dir_tasks: set = set()
    if faults.get("kv_directory_url"):
        dirpub = _FakeDirectoryPublisher(
            faults["kv_directory_url"],
            faults.get("self_url") or "http://127.0.0.1:0",
        )

    def _publish_bg(prompt: str) -> None:
        # the loop holds only WEAK refs to tasks: without a strong ref a
        # publish parked on the publisher lock can be GC'd mid-flight and
        # the claims silently never land (flaky chaos assertions)
        t = asyncio.ensure_future(dirpub.publish_prompt(prompt))
        dir_tasks.add(t)
        t.add_done_callback(dir_tasks.discard)
        if fabric_srv[0] is not None:
            # the published chain is now "resident" on this fake — its
            # fabric listener will serve these keys to pulling peers
            from production_stack_tpu.engine.kv_manager import prefix_hashes
            from production_stack_tpu.engine.tokenizer import ByteTokenizer

            STATE["fabric_resident"].update(
                h.hex()
                for h in prefix_hashes(ByteTokenizer().encode(prompt), 16)
            )

    # -- KV fabric emulation (--fabric; real wire shapes, docs/kv-fabric.md) --
    fabric_enabled = bool(faults.get("fabric", False))
    fabric_fail_rate = float(faults.get("fabric_fail_rate", 0.0))
    fabric_hang = bool(faults.get("fabric_hang", False))
    # boot-epoch generation fences stale pulls, same scheme as the directory
    # publisher (a reborn fake's listener rejects claims on the old epoch)
    fabric_generation = int(time.time() * 1000)
    fabric_srv: list = [None]   # asyncio.Server once started
    fabric_port: list = [0]
    # tiny but structurally real page geometry: frames carry actual
    # [layers, page, kv_heads, head_dim] arrays through encode/decode_frame
    FAB_NLAYERS, FAB_PAGE, FAB_KH, FAB_D = 2, 16, 1, 8

    def _fabric_page(key: str):
        """Deterministic synthetic (k, v) page from the key hex — identical
        bytes on every fake, so cross-engine pull assertions can compare."""
        import hashlib

        import numpy as np

        def arr(tag: str):
            seed = hashlib.blake2b(
                f"{tag}:{key}".encode(), digest_size=8
            ).digest()
            rng = np.random.default_rng(int.from_bytes(seed, "big"))
            return rng.standard_normal(
                (FAB_NLAYERS, FAB_PAGE, FAB_KH, FAB_D), dtype=np.float32
            )

        return arr("k"), arr("v")

    async def _fabric_handle(reader, writer):
        """One fabric peer connection: the same four-op dispatch as the real
        KVFabricServer (kvfabric/server.py), frames via kvoffload.protocol."""
        from production_stack_tpu.kvfabric.wire import (
            FabricWireError,
            decode_frame,
            encode_frame,
        )
        from production_stack_tpu.kvoffload.protocol import (
            read_frame,
            write_frame,
        )

        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                if fabric_hang:
                    # stalled fabric: peers must hit their deadline/breaker
                    await asyncio.Event().wait()
                if fabric_fail_rate and random.random() < fabric_fail_rate:
                    await write_frame(writer, {
                        "ok": False, "error": "injected fabric failure",
                    })
                    continue
                op = hdr.get("op")
                rhdr, rpayload = {"ok": False, "error": f"bad op {op!r}"}, b""
                if op == "fabric_hello":
                    rhdr = {
                        "ok": True, "generation": fabric_generation,
                        "quant": False, "page_size": FAB_PAGE,
                        "nlayers": FAB_NLAYERS,
                    }
                elif op == "fabric_probe":
                    rhdr, rpayload = {"ok": True, "echo": len(payload)}, payload
                elif op == "fabric_pull":
                    expect = hdr.get("expect_generation")
                    if expect is not None and int(expect) != fabric_generation:
                        rhdr = {"ok": False, "error": "stale_generation",
                                "generation": fabric_generation}
                    else:
                        keys = [
                            k for k in (hdr.get("keys") or [])
                            if k in STATE["fabric_resident"]
                        ]
                        if keys:
                            pages = [_fabric_page(k) for k in keys]
                            rpayload = encode_frame(
                                keys,
                                [p[0] for p in pages],
                                [p[1] for p in pages],
                            )
                            STATE["fabric_served"] += len(keys)
                        rhdr = {"ok": True, "found": keys}
                elif op == "fabric_push":
                    try:
                        frame = decode_frame(payload)
                        for k in frame["keys"]:
                            STATE["fabric_resident"].add(k)
                        STATE["fabric_received"] += len(frame["keys"])
                        rhdr = {"ok": True, "stored": len(frame["keys"])}
                    except FabricWireError:
                        rhdr = {"ok": False, "error": "integrity"}
                await write_frame(writer, rhdr, rpayload)
        except Exception:  # noqa: BLE001 - one bad peer must not kill the app
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _fabric_fetch(owner: str, gen, keys: list) -> int:
        """Pull ``keys`` from ``owner``'s fabric listener (async, on the
        fake's own loop — no BlockingClient off-thread here)."""
        from production_stack_tpu.kvfabric.wire import decode_frame
        from production_stack_tpu.kvoffload.protocol import (
            read_frame,
            write_frame,
        )

        sess = await _mig_client()
        async with sess.get(f"{owner}/kv_fabric") as r:
            if r.status != 200:
                return 0
            info = await r.json()
        if not info.get("enabled"):
            return 0
        host, _, port = str(info["addr"]).rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            hdr = {"op": "fabric_pull", "keys": list(keys)}
            if gen is not None:
                hdr["expect_generation"] = int(gen)
            await write_frame(writer, hdr)
            rhdr, payload = await read_frame(reader)
            if not rhdr.get("ok") or not rhdr.get("found"):
                return 0
            frame = decode_frame(payload)
            for k in frame["keys"]:
                STATE["fabric_resident"].add(k)
            return len(frame["keys"])
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _fabric_pull_for_prompt(prompt: str) -> None:
        """Cross-engine resident pull, the fake's twin of the engine's
        DirectoryPuller fabric path: look the prompt chain up in the
        directory and fetch missing pages from the owning peer's fabric
        (generation-fenced). Any failure counts a tier fallback — the blobs
        are in the shared cache server anyway."""
        if dirpub is None or fabric_srv[0] is None:
            return
        from production_stack_tpu.engine.kv_manager import prefix_hashes
        from production_stack_tpu.engine.tokenizer import ByteTokenizer

        hashes = [
            h.hex()
            for h in prefix_hashes(ByteTokenizer().encode(prompt), FAB_PAGE)
        ]
        keys = [h for h in hashes if h not in STATE["fabric_resident"]]
        if not keys:
            return
        try:
            res = await dirpub._request(
                {"op": "dir_lookup_hashes", "hashes": keys}
            )
        except Exception:  # noqa: BLE001 - directory outage: nothing to pull
            return
        resident = res.get("resident") or {}
        gens = res.get("generations") or {}
        owners = [(u, n) for u, n in resident.items() if u != self_url]
        if not owners:
            return
        owner, depth = max(owners, key=lambda kv: kv[1])
        want = keys[:depth]
        try:
            got = await asyncio.wait_for(
                _fabric_fetch(owner, gens.get(owner), want), 5.0
            )
        except Exception:  # noqa: BLE001 - dead/hung peer fabric
            got = 0
        if got:
            STATE["fabric_pulled"] += got
        else:
            STATE["fabric_fallbacks"] += len(want)

    # -- live migration (--migration; real wire shapes, docs/migration.md) --
    migration_enabled = bool(faults.get("migration", True))
    warm_prefetch_n = int(faults.get("warm_prefetch_on_boot") or 0)
    self_url = faults.get("self_url") or "http://127.0.0.1:0"
    mig_session: list = [None]  # lazy shared aiohttp client for ships

    async def _mig_client():
        import aiohttp

        if mig_session[0] is None or mig_session[0].closed:
            mig_session[0] = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=15, sock_connect=5)
            )
        return mig_session[0]

    def _prompt_warm_hit(prompt: str) -> None:
        """--warm-prefetch-on-boot accounting: a prompt whose chain HEAD is
        in the prefetched set would have served a warm prefix hit."""
        if not STATE["prefetched"]:
            return
        from production_stack_tpu.engine.kv_manager import prefix_hashes
        from production_stack_tpu.engine.tokenizer import ByteTokenizer

        hashes = prefix_hashes(ByteTokenizer().encode(prompt), 16)
        if hashes and hashes[0].hex() in STATE["prefetched"]:
            STATE["warm_prefix_hits"] += 1

    async def _maybe_migrate_out(resp, req_id: str, total_out: int) -> bool:
        """Streaming-loop migration hook (chunk-boundary deterministic):
        when /migrate_out froze this stream, report progress, wait for the
        ship decision, and on commit end the leg with the REAL control
        event (no [DONE] — the router's splice takes over). Returns True
        when the stream ended here."""
        mig = STATE["migrating"].get(req_id)
        if mig is None or mig.get("frozen"):
            return False
        mig["sent"] = total_out
        mig["frozen"] = True
        mig["ready"].set()
        await mig["done"].wait()
        STATE["migrating"].pop(req_id, None)
        if not mig.get("commit"):
            return False  # rolled back: keep streaming locally
        await resp.write(
            f"data: {json.dumps({'pstpu_migration': {'target': mig['target'], 'request_id': req_id}})}\n\n".encode()
        )
        STATE["migrations_out"] += 1
        _push_slo_record(model, req_id, "migrated")
        return True

    async def migratable(request):
        """Fleet-controller victim listing, same shape as the real engine."""
        out = [
            {
                "request_id": rid,
                "output_tokens": int(STATE["progress"].get(rid, 0)),
                "prompt_tokens": 10,
                "age_s": 0.0,
                "priority": (STATE["meta"].get(rid) or {}).get(
                    "priority", "interactive"
                ),
                "migratable": migration_enabled
                and rid not in STATE["migrating"],
                "reason": None if migration_enabled else "migration disabled",
            }
            for rid in list(STATE["streams"])
        ]
        return web.json_response({"requests": out})

    async def migrate_out(request):
        """Freeze -> ship (sealed real-shape snapshot) -> commit/rollback,
        mirroring the real engine's /migrate_out semantics."""
        if not migration_enabled:
            return web.json_response(
                {"migrated": False, "error": "migration disabled"}, status=501
            )
        try:
            body = await request.json()
            rid = body["request_id"]
            target = str(body["target_url"]).rstrip("/")
        except (KeyError, TypeError, ValueError):
            return web.json_response(
                {"migrated": False,
                 "error": "request_id and target_url required"}, status=400,
            )
        if rid not in STATE["streams"] or rid not in STATE["inflight"]:
            return web.json_response(
                {"migrated": False, "error": f"{rid!r} is not a live stream"},
                status=409,
            )
        if rid in STATE["migrating"]:
            return web.json_response(
                {"migrated": False, "error": "migration already in progress"},
                status=409,
            )
        entry = {
            "ready": asyncio.Event(), "done": asyncio.Event(),
            "commit": False, "target": target, "sent": 0, "frozen": False,
        }
        STATE["migrating"][rid] = entry
        try:
            await asyncio.wait_for(entry["ready"].wait(), 5.0)
        except asyncio.TimeoutError:
            STATE["migrating"].pop(rid, None)
            entry["done"].set()
            return web.json_response(
                {"migrated": False,
                 "error": "stream never reached a migration point"},
                status=409,
            )
        from production_stack_tpu.migration import (
            SequenceSnapshot,
            snapshot_to_wire,
        )

        meta = dict(STATE["meta"].get(rid) or {})
        max_tokens = int(meta.get("max_tokens", entry["sent"] + 1))
        snap = SequenceSnapshot(
            request_id=rid, model=model, page_size=16,
            # synthetic but structurally real: 10 prompt ids + one id per
            # emitted token (the receiving fake only needs the lengths)
            tokens=list(range(10)) + [72] * entry["sent"],
            prompt_len=10, output_len=entry["sent"],
            params={
                "max_tokens": max_tokens, "temperature": 0.0, "top_k": 0,
                "top_p": 1.0, "stop": [], "ignore_eos": True,
                "min_tokens": 0, "seed": None, "presence_penalty": 0.0,
                "frequency_penalty": 0.0, "repetition_penalty": 1.0,
            },
            page_hashes=[], meta=meta,
        )
        ok, detail = False, ""
        try:
            sess = await _mig_client()
            async with sess.post(
                f"{target}/migrate_in", data=snapshot_to_wire(snap),
                headers={"Content-Type": "application/octet-stream"},
            ) as r2:
                detail = (await r2.text())[:200]
                ok = r2.status == 200
        except Exception as e:  # noqa: BLE001 - ship failure rolls back
            detail = repr(e)
        entry["commit"] = ok
        entry["done"].set()
        if not ok:
            return web.json_response(
                {"migrated": False, "error": detail or "target refused"},
                status=502,
            )
        return web.json_response(
            {"migrated": True, "target": target, "pages_moved": 0}
        )

    async def migrate_in(request):
        """Accept a sealed snapshot (REAL parse + validation path) and park
        the synthetic continuation for /migrate_attach."""
        if not migration_enabled:
            return web.json_response(
                {"accepted": False, "error": "migration disabled"}, status=501
            )
        if STATE["draining"]:
            return web.json_response(
                {"accepted": False, "error": "draining"}, status=503
            )
        from production_stack_tpu.kvoffload.serde import KVIntegrityError
        from production_stack_tpu.migration import (
            continuation_params,
            snapshot_from_wire,
        )

        data = await request.read()
        try:
            snap = snapshot_from_wire(data)
            params = continuation_params(snap)
        except (KVIntegrityError, ValueError, KeyError, TypeError) as e:
            return web.json_response(
                {"accepted": False, "error": f"bad snapshot: {e}"}, status=400
            )
        if snap.model != model:
            return web.json_response(
                {"accepted": False,
                 "error": f"model mismatch: {snap.model!r} != {model!r}"},
                status=409,
            )
        rid = snap.request_id
        if rid in STATE["parked"] or rid in STATE["streams"]:
            return web.json_response(
                {"accepted": False, "error": f"{rid!r} already live here"},
                status=409,
            )
        STATE["parked"][rid] = {
            "snap": snap, "remaining": params.max_tokens,
            "t": time.monotonic(),
        }
        STATE["migrations_in"] += 1

        def _expire():
            if STATE["parked"].pop(rid, None) is not None:
                print(f"fake-engine: parked {rid} expired unattached",
                      flush=True)

        asyncio.get_running_loop().call_later(30.0, _expire)
        return web.json_response({
            "accepted": True, "request_id": rid,
            "restorable_pages": len(snap.page_hashes),
        })

    async def migrate_attach(request):
        """Stream a parked continuation in the real chunk/usage/[DONE] wire
        shapes; supports chained migration (the continuation can itself be
        migrated out again mid-attach)."""
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001
            body = {}
        rid = body.get("request_id") or request.query.get("request_id")
        deadline = time.monotonic() + 10.0
        parked = STATE["parked"].pop(rid, None)
        while parked is None and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
            parked = STATE["parked"].pop(rid, None)
        if parked is None:
            return web.json_response(
                {"error": {"message": f"no parked continuation for {rid!r}"}},
                status=404,
            )
        snap = parked["snap"]
        meta = snap.meta
        chat = bool(meta.get("chat"))
        oid = meta.get("oid") or (("chatcmpl-" if chat else "cmpl-") + rid)
        created = int(meta.get("created") or time.time())
        kind = "chat.completion" if chat else "text_completion"
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream", "X-Request-Id": rid}
        )
        await resp.prepare(request)
        # the continuation is a live, re-migratable stream on THIS process
        STATE["running"] += 1
        STATE["running_peak"] = max(STATE["running_peak"], STATE["running"])
        STATE["inflight"][rid] = asyncio.current_task()
        STATE["streams"].add(rid)
        STATE["meta"][rid] = {
            **meta, "max_tokens": int(snap.params.get("max_tokens", 1)),
        }
        emitted = 0
        try:
            for _j in range(parked["remaining"]):
                if await _maybe_migrate_out(
                    resp, rid, snap.output_len + emitted
                ):
                    await resp.write_eof()
                    return resp
                STATE["progress"][rid] = snap.output_len + emitted
                delta = {"content": "Hello "} if chat else None
                choice = (
                    {"index": 0, "delta": delta, "finish_reason": None}
                    if chat
                    else {"index": 0, "text": "Hello ", "finish_reason": None}
                )
                await resp.write(
                    f"data: {json.dumps({'id': oid, 'object': 'chat.completion.chunk' if chat else 'text_completion', 'created': created, 'model': model, 'choices': [choice]})}\n\n".encode()
                )
                emitted += 1
                await asyncio.sleep(1.0 / speed)
            prompt_tokens = int(meta.get("prompt_tokens") or snap.prompt_len)
            completion = snap.output_len + emitted
            await resp.write(
                f"data: {json.dumps({'id': oid, 'object': f'{kind}.chunk' if chat else kind, 'created': created, 'model': model, 'choices': [], 'usage': {'prompt_tokens': prompt_tokens, 'completion_tokens': completion, 'total_tokens': prompt_tokens + completion}})}\n\n".encode()
            )
            await resp.write(b"data: [DONE]\n\n")
            STATE["completed"] += 1
            _push_slo_record(
                model, rid, "ok", output_tokens=completion,
                priority=meta.get("priority", "interactive"),
            )
            await resp.write_eof()
            return resp
        except asyncio.CancelledError:
            _push_slo_record(model, rid, "abort",
                             priority=meta.get("priority", "interactive"))
            raise
        finally:
            STATE["running"] -= 1
            STATE["inflight"].pop(rid, None)
            STATE["streams"].discard(rid)
            STATE["progress"].pop(rid, None)
            STATE["meta"].pop(rid, None)
            STATE["migrating"].pop(rid, None)

    def _hard_crash():
        """kill -9 semantics: no drain, no flushed buffers, no cleanup —
        exactly what a warm-start manifest's periodic spill must survive."""
        import os
        import sys

        print("fake-engine: injected hard crash (--crash-after-n)", flush=True)
        sys.stdout.flush()
        os._exit(9)

    def shed_response(reason: str, req_id: str = "",
                      priority: str = "interactive"):
        STATE["shed"] += 1
        STATE["shed_by_class"][
            priority if priority in STATE["shed_by_class"] else "interactive"
        ] += 1
        # flight-recorder shed event + burst-triggered anomaly dump, same
        # trigger shape as the real engine (_note_shed): the overload chaos
        # scenario asserts a parseable dump lands during the shed storm
        fr = get_flightrecorder()
        now = time.monotonic()
        STATE["shed_times"].append(now)
        fr.record(
            "shed", step=STATE["served"], reason=reason, seq_id=req_id,
            running=STATE["running"],
        )
        if sum(1 for t in list(STATE["shed_times"]) if now - t <= 5.0) >= 5:
            fr.dump_async("shed_burst")  # keep the event loop serving
        _push_slo_record(model, req_id or "unknown", "shed",
                         priority=priority)
        return web.json_response(
            {"error": {"message": f"saturated (injected: {reason})",
                       "type": "overloaded_error", "code": 429}},
            status=429,
            headers={"Retry-After": retry_after},
        )

    async def health(request):
        if STATE["draining"]:
            return web.Response(status=503, text="draining")
        return web.Response(text="")

    async def models(request):
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": model,
                        "object": "model",
                        "created": int(time.time()),
                        "owned_by": "fake-engine",
                    }
                ],
            }
        )

    def _p99(window) -> float:
        snap = sorted(window)
        if not snap:
            return 0.0
        return round(snap[min(len(snap) - 1, int(len(snap) * 0.99))], 3)

    async def metrics(request):
        saturated = int(
            saturate_after_n is not None
            and STATE["running"] >= int(saturate_after_n)
        )
        saturated_batch = int(
            saturate_after_n is not None
            and STATE["running"]
            >= max(0, int(saturate_after_n) - interactive_reserve)
        )
        text = (
            f'vllm:num_requests_running{{model_name="{model}"}} {STATE["running"]}\n'
            f'vllm:num_requests_waiting{{model_name="{model}"}} 0\n'
            f'vllm:gpu_cache_usage_perc{{model_name="{model}"}} 0.42\n'
            f'vllm:gpu_prefix_cache_hits_total{{model_name="{model}"}} 10\n'
            f'vllm:gpu_prefix_cache_queries_total{{model_name="{model}"}} 20\n'
            f'vllm:engine_saturated{{model_name="{model}"}} {saturated}\n'
            # class-aware saturation + interactive latency surface, same
            # names as the real engine: the fleet controller's
            # latency_protect and the router's class routing scrape these
            f'vllm:engine_saturated_batch{{model_name="{model}"}} {saturated_batch}\n'
            f'vllm:interactive_ttft_p99_ms{{model_name="{model}"}} {_p99(STATE["interactive_ttft_ms"])}\n'
            f'vllm:interactive_itl_p99_ms{{model_name="{model}"}} {_p99(STATE["interactive_itl_ms"])}\n'
            # serving-mesh advert (--tensor-parallel): the router's scraper
            # and the fleet controller read capacity shape through this
            f'vllm:tensor_parallel_degree{{model_name="{model}"}} {tensor_parallel}\n'
            f'vllm:num_requests_shed_total{{model_name="{model}"}} {STATE["shed"]}\n'
            # fake-only observability: bounded-queue proof for overload tests,
            # per-process served/completed/abort counters for restart + replay
            # chaos assertions (served resets with the process, so a reborn
            # backend's counter climbing from 0 proves traffic returned)
            f'fake:running_peak{{model_name="{model}"}} {STATE["running_peak"]}\n'
            f'fake:served_total{{model_name="{model}"}} {STATE["served"]}\n'
            f'fake:completed_total{{model_name="{model}"}} {STATE["completed"]}\n'
            f'fake:abort_requests_total{{model_name="{model}"}} {STATE["aborts"]}\n'
            # per-class served/shed split: mixed-class-overload asserts the
            # shed distribution (batch absorbs everything until the
            # interactive reserve is exhausted) through these
            f'fake:served_by_class_total{{model_name="{model}",priority="interactive"}} {STATE["served_by_class"]["interactive"]}\n'
            f'fake:served_by_class_total{{model_name="{model}",priority="batch"}} {STATE["served_by_class"]["batch"]}\n'
            f'fake:shed_by_class_total{{model_name="{model}",priority="interactive"}} {STATE["shed_by_class"]["interactive"]}\n'
            f'fake:shed_by_class_total{{model_name="{model}",priority="batch"}} {STATE["shed_by_class"]["batch"]}\n'
            # live-migration + scale-up warm-up surface (chaos scale-cycle
            # assertions; real engines export vllm:migrations_*_total)
            f'fake:migrations_out_total{{model_name="{model}"}} {STATE["migrations_out"]}\n'
            f'fake:migrations_in_total{{model_name="{model}"}} {STATE["migrations_in"]}\n'
            f'fake:warm_prefetch_chunks{{model_name="{model}"}} {len(STATE["prefetched"])}\n'
            f'fake:warm_prefix_hits_total{{model_name="{model}"}} {STATE["warm_prefix_hits"]}\n'
        )
        if fabric_enabled:
            # KV fabric surface, same vllm: names as the real engine so the
            # router scraper, fleet controller, and chaos assertions read
            # the fake identically (docs/kv-fabric.md)
            fabric_up = fabric_srv[0] is not None and not STATE["fabric_down"]
            text += (
                f'vllm:kv_fabric_pushed_pages_total{{model_name="{model}"}} 0\n'
                f'vllm:kv_fabric_pulled_pages_total{{model_name="{model}"}} {STATE["fabric_pulled"]}\n'
                f'vllm:kv_fabric_served_pages_total{{model_name="{model}"}} {STATE["fabric_served"]}\n'
                f'vllm:kv_fabric_received_pages_total{{model_name="{model}"}} {STATE["fabric_received"]}\n'
                f'vllm:kv_fabric_fallbacks_total{{model_name="{model}"}} {STATE["fabric_fallbacks"]}\n'
                f'vllm:kv_fabric_queue_depth{{model_name="{model}"}} 0\n'
                # synthetic probed-bandwidth gauge: up = a fast deterministic
                # link, down = 0 — drives the router's transfer-cost pick
                f'vllm:kv_fabric_peer_bandwidth_bytes_per_sec{{model_name="{model}",peer="self"}} '
                f"{1000000000 if fabric_up else 0}\n"
            )
        if restore_pages:
            # warm-restart modelling (--restart-restore-pages): the same
            # surface a real --warm-start engine exports after restore
            text += (
                f'vllm:warm_start_restored_pages{{model_name="{model}"}} '
                f"{restore_pages}\n"
                f'vllm:warm_start_manifest_age_seconds{{model_name="{model}"}} '
                f"{time.time() - start_time:.3f}\n"
                f'vllm:kv_corrupt_pages_total{{model_name="{model}"}} 0\n'
            )
        # per-phase histograms, same names as the real engine's /metrics so
        # smoke tests and dashboard queries exercise the fake identically
        text += "\n".join(render_phase_histograms(f'model_name="{model}"')) + "\n"
        # span-loss + flight-recorder health, same surface as the real engine
        text += "\n".join(render_collector_metrics(f'model_name="{model}"')) + "\n"
        text += "\n".join(
            render_flightrecorder_metrics(f'model_name="{model}"')
        ) + "\n"
        return web.Response(text=text, content_type="text/plain")

    async def traces(request):
        payload, status = export_for_query(request.query)
        return web.json_response(payload, status=status)

    async def slo_records(request):
        """Same wire contract as the real engine's GET /slo_records."""
        try:
            since = int(request.query.get("since", "0"))
        except (TypeError, ValueError):
            return web.json_response({"error": "since must be an int"}, status=400)
        snap = list(STATE["slo_records"])
        head = snap[-1]["seq"] if snap else 0
        records = [r for r in snap if r["seq"] > since]
        return web.json_response({
            "model": model,
            "since": since,
            "next": max((r["seq"] for r in records), default=since),
            "head": head,
            "records": records,
        })

    async def flightrecorder_export(request):
        payload, status = flightrecorder.export_for_query(request.query)
        return web.json_response(payload, status=status)

    async def completions(request):
        return await _generate(request, chat=False)

    async def chat(request):
        return await _generate(request, chat=True)

    async def _generate(request, chat: bool):
        if STATE["sleeping"]:
            return web.json_response({"error": "sleeping"}, status=503)
        if STATE["draining"]:
            return web.json_response(
                {"error": {"message": "engine is draining for shutdown"}},
                status=503,
            )
        body = await request.json()
        max_tokens = int(body.get("max_tokens", 16))
        stream = bool(body.get("stream", False))
        prompt_text = _prompt_text(body, chat)
        req_id = request.headers.get("X-Request-Id", uuid.uuid4().hex)
        # SLO class, same resolution order as the real engine's api_server:
        # X-Priority header wins, then a body field, unknown -> interactive
        priority = str(
            request.headers.get("X-Priority")
            or body.get("priority") or "interactive"
        ).strip().lower()
        if priority not in ("interactive", "batch"):
            priority = "interactive"
        uid = request.headers.get("x-user-id")
        if uid:
            # visible marker for tests asserting user-id header propagation
            print(f"x-user-id={uid}", flush=True)
        # fault injection: 500s fire BEFORE a slot is held (connect-stage
        # failure from the router's point of view)
        STATE["served"] += 1
        STATE["served_by_class"][priority] += 1
        # hard crash: request N+1 and later never answer — the process dies
        # abruptly (mid-stream when streaming, pre-response otherwise)
        crashing = (
            crash_after_n is not None and STATE["served"] > int(crash_after_n)
        )
        if crashing and not stream:
            _hard_crash()
        if fail_first_n and STATE["served"] <= fail_first_n:
            return web.json_response(
                {"error": {"message": "injected failure (fail-first-n)"}}, status=500
            )
        if fail_rate and random.random() < fail_rate:
            return web.json_response(
                {"error": {"message": "injected failure (fail-rate)"}}, status=500
            )
        # admission control simulation: shed BEFORE taking a slot, so the
        # in-flight count is provably bounded by saturate_after_n (the
        # overload chaos scenario asserts on running_peak). Class-aware:
        # batch hits its bound --interactive-reserve slots early, so the
        # reserved tail of capacity only ever admits interactive work
        if saturate_after_n is not None:
            bound = int(saturate_after_n)
            if priority == "batch":
                bound = max(0, bound - interactive_reserve)
            if STATE["running"] >= bound:
                return shed_response("saturate-after-n", req_id, priority)
        if shed_rate and random.random() < shed_rate:
            return shed_response("shed-rate", req_id, priority)
        # distributed tracing, same span model as the real engine
        # (engine.request > queue/prefill/decode) so router e2e tests can
        # assert full-stack trace propagation without a TPU
        collector = get_collector()
        trace_ctx = collector.root_from_headers(request.headers).child()
        t_accept = time.time()
        STATE["running"] += 1
        STATE["running_peak"] = max(STATE["running_peak"], STATE["running"])
        STATE["total"] += 1
        # synthetic flight-recorder feed, same event shapes as the real
        # engine loop (sched + kv per dispatch, cross-linked by trace id) so
        # anomaly-dump consumers are testable without a TPU
        fr = get_flightrecorder()
        fr_trace = trace_ctx.trace_id if trace_ctx.sampled else None
        fr.record(
            "sched", step=STATE["served"], batch_kind="decode",
            rows=STATE["running"], bursts=1, chunk_tokens=0,
            seq_ids=[req_id], trace_ids=[fr_trace] if fr_trace else [],
            gate={"backlog_tokens": 0, "decode_demand": STATE["running"],
                  "alternate": False, "waiting": 0},
            running=STATE["running"], waiting=0,
            trace_id=fr_trace,
        )
        fr.record(
            "kv", step=STATE["served"], op="alloc",
            pages=max(1, max_tokens // 16), trace_id=fr_trace,
        )
        # registered while holding a slot so POST /abort can cancel this
        # handler and free the slot, like the real engine's abort endpoint
        STATE["inflight"][req_id] = asyncio.current_task()
        created = int(time.time())
        oid = ("chatcmpl-" if chat else "cmpl-") + req_id
        # presentation meta a migration snapshot carries (real-shape parity)
        STATE["meta"][req_id] = {
            "oid": oid, "chat": chat, "created": created, "model": model,
            "prompt_tokens": 10, "max_tokens": max_tokens,
            # rides the migration snapshot so the target resumes the stream
            # in the same SLO class (real api_server parity)
            "priority": priority,
        }
        _prompt_warm_hit(prompt_text)
        if fabric_srv[0] is not None and dirpub is not None:
            # fabric-first KV acquisition before "prefill" (the real
            # engine's DirectoryPuller fabric path): pull the prompt chain
            # from the resident owner, count a fallback on any failure
            await _fabric_pull_for_prompt(prompt_text)

        def _phase(name, start, dur, **attrs):
            collector.record(
                name, trace_ctx.child(), start, dur,
                seq_id=req_id, **attrs,
            )

        def _decode_done(t_first):
            t_done = time.time()
            _phase("engine.decode", t_first, t_done - t_first,
                   output_tokens=max_tokens, finish_reason="length")
            if max_tokens > 1:
                decode_step_time_hist.observe(
                    (t_done - t_first) / (max_tokens - 1)
                )
            # terminal SLO record: measured TTFT; ITL p99 is --slo-itl-ms
            # when injected (drives router-side violation counters), else
            # the stream's real pacing
            measured_itl = (
                (t_done - t_first) * 1000 / max(1, max_tokens - 1)
                if max_tokens > 1 else None
            )
            rec_ttft = (t_first - t_accept) * 1000
            rec_itl = (
                float(slo_itl_ms) if slo_itl_ms is not None else measured_itl
            )
            if priority == "interactive" and interactive_slo_degrade_ms > 0:
                # injected SLO degradation: the REPORTED interactive
                # latencies inflate (records + p99 gauges) without slowing
                # the stream — chaos drives latency_protect off this
                rec_ttft += interactive_slo_degrade_ms
                rec_itl = (rec_itl or 0.0) + interactive_slo_degrade_ms
            if priority == "interactive":
                STATE["interactive_ttft_ms"].append(rec_ttft)
                if rec_itl is not None:
                    STATE["interactive_itl_ms"].append(rec_itl)
            _push_slo_record(
                model, req_id, "ok",
                ttft_ms=rec_ttft,
                itl_p99_ms=rec_itl,
                output_tokens=max_tokens,
                queue_ms=0.0,
                e2e_ms=(t_done - t_accept) * 1000,
                trace_id=fr_trace,
                priority=priority,
            )

        try:
            if hang:
                # hung engine: the slot stays pinned until /abort (or process
                # death) — exactly the failure the router's TTFT deadline +
                # engine abort must reclaim
                await asyncio.Event().wait()
            t_q = time.time()
            _phase("engine.queue", t_accept, t_q - t_accept)
            queue_time_hist.observe(t_q - t_accept)
            if compile_stall_ms > 0 and not STATE["compile_stalled"]:
                # one injected compile stall on the first generation: the
                # first request of a real engine pays tracing + XLA compile,
                # and the recorder's compile event is how a postmortem tells
                # a compile stall from a scheduling stall
                STATE["compile_stalled"] = True
                fr.record(
                    "compile", step=STATE["served"],
                    event="backend_compile",
                    seconds=round(compile_stall_ms / 1000.0, 4),
                    trace_id=fr_trace,
                )
                await asyncio.sleep(compile_stall_ms / 1000.0)
            await asyncio.sleep(ttft)  # injected prefill time
            t_first = time.time()
            _phase("engine.prefill", t_q, t_first - t_q, prompt_tokens=10)
            prefill_time_hist.observe(t_first - t_q)
            if not stream:
                await asyncio.sleep(max_tokens / speed)
                _decode_done(t_first)
                STATE["completed"] += 1
                if dirpub is not None:
                    # deterministic publish on completion (ISSUE 9): this
                    # prompt's chunk chain is now "resident" on this fake
                    _publish_bg(prompt_text)
                text = "Hello " * max_tokens
                choice = (
                    {"index": 0, "message": {"role": "assistant", "content": text},
                     "finish_reason": "length"}
                    if chat
                    else {"index": 0, "text": text, "finish_reason": "length"}
                )
                return web.json_response(
                    {
                        "id": oid, "object": "chat.completion" if chat else "text_completion",
                        "created": created, "model": model, "choices": [choice],
                        "usage": {
                            "prompt_tokens": 10, "completion_tokens": max_tokens,
                            "total_tokens": 10 + max_tokens,
                        },
                    },
                    # X-Priority echo: e2e tests assert the class the engine
                    # actually resolved, not just what the client sent
                    headers={"X-Request-Id": req_id, "X-Priority": priority},
                )
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream",
                         "X-Request-Id": req_id, "X-Priority": priority}
            )
            await resp.prepare(request)
            STATE["streams"].add(req_id)  # migratable from the first chunk on
            for i in range(max_tokens):
                # live migration: a frozen stream hands off at this chunk
                # boundary (control event written, no [DONE]) or resumes
                if await _maybe_migrate_out(resp, req_id, i):
                    await resp.write_eof()
                    return resp
                STATE["progress"][req_id] = i
                # mid-stream hard crash: one chunk leaves first when the
                # stream has more than one, then the whole process vanishes
                # without a FIN or a drain; a single-token stream crashes on
                # its only chunk (the flag must fire for every request shape)
                if crashing and i >= min(1, max_tokens - 1):
                    _hard_crash()
                if fail_after_chunks is not None and i >= int(fail_after_chunks):
                    # mid-stream truncation: drop the TCP connection without
                    # a chunked terminator, so the proxy sees a payload error
                    request.transport.close()
                    return resp
                if hang_after_chunks is not None and i >= int(hang_after_chunks):
                    # mid-stream stall: chunks stop flowing but the
                    # connection stays up — only the router's inter-chunk
                    # deadline (or /abort) ends this
                    await asyncio.Event().wait()
                delta = {"content": "Hello "} if chat else None
                choice = (
                    {"index": 0, "delta": delta, "finish_reason": None}
                    if chat
                    else {"index": 0, "text": "Hello ", "finish_reason": None}
                )
                await resp.write(
                    f"data: {json.dumps({'id': oid, 'object': 'chat.completion.chunk' if chat else 'text_completion', 'created': created, 'model': model, 'choices': [choice]})}\n\n".encode()
                )
                await asyncio.sleep(1.0 / speed)
            _decode_done(t_first)
            STATE["completed"] += 1
            if dirpub is not None:
                _publish_bg(prompt_text)
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        except asyncio.CancelledError:
            # router-initiated abort (POST /abort) or client disconnect: the
            # real engine attributes these a terminal 'abort' record too
            _push_slo_record(model, req_id, "abort", trace_id=fr_trace,
                             priority=priority)
            raise
        finally:
            STATE["running"] -= 1
            STATE["inflight"].pop(req_id, None)
            STATE["streams"].discard(req_id)
            STATE["progress"].pop(req_id, None)
            STATE["meta"].pop(req_id, None)
            STATE["migrating"].pop(req_id, None)
            collector.record(
                "engine.request", trace_ctx, t_accept,
                time.time() - t_accept, request_id=req_id, model=model,
            )

    async def abort(request):
        """Router-initiated abort, same contract as the real engine's
        POST /abort: cancel the in-flight handler, freeing the slot."""
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001
            body = {}
        rid = body.get("request_id") or request.query.get("request_id")
        STATE["aborts"] += 1
        task = STATE["inflight"].pop(rid, None)
        if task is not None:
            task.cancel()
        return web.json_response({"request_id": rid, "aborted": task is not None})

    async def sleep(request):
        STATE["sleeping"] = True
        return web.Response(text="")

    async def wake_up(request):
        STATE["sleeping"] = False
        return web.Response(text="")

    async def is_sleeping(request):
        return web.json_response({"is_sleeping": STATE["sleeping"]})

    async def tokenize(request):
        body = await request.json()
        text = body.get("prompt", "")
        return web.json_response(
            {"tokens": list(text.encode()), "count": len(text.encode()), "max_model_len": 4096}
        )

    # -- real-engine route parity (graftcheck GC005): every engine route the
    # router proxies or probes must answer here too, or e2e runs against the
    # fake 404 where production would not. Deterministic dummy payloads in
    # the real wire shapes.

    async def detokenize(request):
        body = await request.json()
        toks = body.get("tokens", [])
        return web.json_response(
            {"prompt": bytes(t & 0xFF for t in toks).decode(errors="replace")}
        )

    def _fake_embedding(text: str, dim: int = 8) -> list[float]:
        """Deterministic unit vector from the text bytes — stable across
        processes so reranking/scoring assertions are reproducible."""
        import hashlib

        h = hashlib.blake2b(str(text).encode(), digest_size=dim).digest()
        v = [b / 255.0 + 1e-3 for b in h]
        n = sum(x * x for x in v) ** 0.5
        return [x / n for x in v]

    async def embeddings(request):
        body = await request.json()
        raw = body.get("input", [])
        items = [raw] if isinstance(raw, str) else list(raw)
        if not items:
            return web.json_response(
                {"error": {"message": "'input' is required"}}, status=400
            )
        return web.json_response({
            "object": "list",
            "model": body.get("model", model),
            "data": [
                {"object": "embedding", "index": i,
                 "embedding": _fake_embedding(t)}
                for i, t in enumerate(items)
            ],
            "usage": {"prompt_tokens": len(items), "total_tokens": len(items)},
        })

    def _cosine(a: list, b: list) -> float:
        return sum(x * y for x, y in zip(a, b))

    async def rerank(request):
        body = await request.json()
        try:
            query, documents = body["query"], list(body["documents"])
        except (KeyError, TypeError) as e:
            return web.json_response(
                {"error": {"message": f"invalid request: {e}"}}, status=400
            )
        qv = _fake_embedding(query)
        scores = [_cosine(qv, _fake_embedding(d)) for d in documents]
        top_n = int(body.get("top_n", len(documents)))
        order = sorted(range(len(documents)), key=lambda i: -scores[i])[:top_n]
        return web.json_response({
            "id": f"rerank-{uuid.uuid4().hex[:16]}",
            "model": body.get("model", model),
            "results": [
                {"index": i, "document": {"text": documents[i]},
                 "relevance_score": scores[i]}
                for i in order
            ],
        })

    async def score(request):
        body = await request.json()
        try:
            t1, t2 = body["text_1"], body["text_2"]
        except (KeyError, TypeError) as e:
            return web.json_response(
                {"error": {"message": f"invalid request: {e}"}}, status=400
            )
        left = [t1] if isinstance(t1, str) else list(t1)
        right = [t2] if isinstance(t2, str) else list(t2)
        if len(left) == 1:
            left = left * len(right)
        if len(left) != len(right):
            return web.json_response(
                {"error": {"message": "text_1 and text_2 lengths do not match"}},
                status=400,
            )
        return web.json_response({
            "id": f"score-{uuid.uuid4().hex[:16]}",
            "object": "list",
            "model": body.get("model", model),
            "data": [
                {"index": i, "object": "score",
                 "score": _cosine(_fake_embedding(a), _fake_embedding(b))}
                for i, (a, b) in enumerate(zip(left, right))
            ],
            "usage": {"prompt_tokens": len(left) + len(right)},
        })

    async def kv_fabric_info(request):
        """Same advert contract as the real engine's GET /kv_fabric:
        answers enabled:false when the fabric is off or downed."""
        if fabric_srv[0] is None or STATE["fabric_down"]:
            return web.json_response({"enabled": False})
        return web.json_response({
            "enabled": True,
            "addr": f"127.0.0.1:{fabric_port[0]}",
            "generation": fabric_generation,
            "quant": False,
            "page_size": FAB_PAGE,
        })

    async def fabric_down(request):
        """Chaos hook (fake-only): close the fabric listener mid-load while
        the HTTP plane keeps serving — peers' pulls must fall back to the
        tier path with zero client-visible errors."""
        STATE["fabric_down"] = True
        if fabric_srv[0] is not None:
            fabric_srv[0].close()
        print("fake-engine: fabric listener downed (/fabric_down)", flush=True)
        return web.json_response({"fabric": "down"})

    async def version(request):
        return web.json_response({"version": "fake-engine"})

    async def metrics_reset(request):
        """Same debug contract as the real engine's POST /metrics/reset:
        clear the per-phase sample windows so a bench phase's quantiles
        describe that phase (counters stay)."""
        from production_stack_tpu.tracing import reset_phase_histograms

        reset_phase_histograms()
        get_collector().reset()
        get_flightrecorder().reset()
        return web.json_response({"status": "ok"})

    # same client_max_size as the real engine: /migrate_in snapshots for
    # long-context streams exceed aiohttp's 1 MiB default
    app = web.Application(client_max_size=64 << 20)
    if dirpub is not None:
        async def _dir_register(app):
            await dirpub.register()  # eager, so a reborn fake re-fences fast
            if warm_prefetch_n > 0:
                # scale-up warm-up modelling: pull the fleet's top warm
                # chunks at boot (the real engine does this BEFORE /ready)
                try:
                    hashes = await dirpub.top_prefixes(warm_prefetch_n)
                    STATE["prefetched"] = set(hashes)
                    print(
                        f"fake-engine: warm-prefetched {len(hashes)} "
                        "fleet-warm chunks", flush=True,
                    )
                except Exception as e:  # noqa: BLE001 - cold boot, not fatal
                    print(f"fake-engine: warm prefetch failed: {e}", flush=True)

        app.on_startup.append(_dir_register)

    if fabric_enabled:
        async def _fabric_start(app):
            fabric_srv[0] = await asyncio.start_server(
                _fabric_handle, "127.0.0.1", 0
            )
            fabric_port[0] = fabric_srv[0].sockets[0].getsockname()[1]
            print(
                f"fake-engine: kv fabric listening on "
                f"127.0.0.1:{fabric_port[0]}", flush=True,
            )

        async def _fabric_stop(app):
            if fabric_srv[0] is not None:
                fabric_srv[0].close()

        app.on_startup.append(_fabric_start)
        app.on_cleanup.append(_fabric_stop)

    async def _close_mig_session(app):
        if mig_session[0] is not None and not mig_session[0].closed:
            await mig_session[0].close()

    app.on_cleanup.append(_close_mig_session)
    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/v1/traces", traces)
    app.router.add_get("/slo_records", slo_records)
    app.router.add_get("/v1/debug/flightrecorder", flightrecorder_export)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/abort", abort)
    app.router.add_get("/kv_fabric", kv_fabric_info)
    app.router.add_post("/fabric_down", fabric_down)
    app.router.add_get("/migratable", migratable)
    app.router.add_post("/migrate_out", migrate_out)
    app.router.add_post("/migrate_in", migrate_in)
    app.router.add_post("/migrate_attach", migrate_attach)
    app.router.add_post("/sleep", sleep)
    app.router.add_post("/wake_up", wake_up)
    app.router.add_get("/is_sleeping", is_sleeping)
    app.router.add_post("/tokenize", tokenize)
    app.router.add_post("/detokenize", detokenize)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/v1/rerank", rerank)
    app.router.add_post("/v2/rerank", rerank)
    app.router.add_post("/v1/score", score)
    app.router.add_get("/version", version)
    app.router.add_post("/metrics/reset", metrics_reset)
    return app


async def _serve_until_sigterm(app, port: int) -> None:
    """Run the app; on SIGTERM/SIGINT drain like the real engine: /health
    flips 503 (readiness pulls the pod), in-flight requests get a bounded
    window to finish, then the server exits cleanly."""
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, port=port, shutdown_timeout=1.0)
    await site.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    STATE["draining"] = True
    # SIGTERM anomaly dump, same trigger as the real engine's drain path
    # (rolling-restart chaos parses these for the pre-restart window)
    get_flightrecorder().dump("sigterm_drain", force=True)
    deadline = time.time() + 5.0
    while STATE["running"] > 0 and time.time() < deadline:
        await asyncio.sleep(0.1)
    await runner.cleanup()


def main():
    p = argparse.ArgumentParser("fake-tpu-engine")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--model", default="fake/model")
    p.add_argument("--speed", type=float, default=100.0, help="tokens per second")
    p.add_argument("--ttft", type=float, default=0.0, help="injected TTFT seconds")
    p.add_argument("--model-label", default=None)
    # fault injection (router failure-domain tests)
    p.add_argument("--fail-rate", type=float, default=0.0,
                   help="probability a generation request 500s")
    p.add_argument("--fail-first-n", type=int, default=0,
                   help="first N generation requests 500, then recover")
    p.add_argument("--fail-after-chunks", type=int, default=None,
                   help="drop the connection after N streamed chunks")
    p.add_argument("--hang", action="store_true",
                   help="accept generation requests but never respond")
    p.add_argument("--hang-after-chunks", type=int, default=None,
                   help="stall the stream after N chunks (connection stays up)")
    p.add_argument("--saturate-after-n", type=int, default=None,
                   help="shed (429 + Retry-After) generation requests "
                        "arriving while N are already in flight")
    p.add_argument("--shed-rate", type=float, default=0.0,
                   help="probability a generation request is shed with "
                        "429 + Retry-After")
    p.add_argument("--retry-after", type=float, default=1.0,
                   help="Retry-After seconds advertised on shed responses")
    p.add_argument("--crash-after-n", type=int, default=None,
                   help="hard-crash the process (os._exit, no drain) once N "
                        "generation requests have been accepted — mid-stream "
                        "when streaming")
    p.add_argument("--restart-restore-pages", type=int, default=None,
                   help="model a warm restart: advertise "
                        "vllm:warm_start_restored_pages N on /metrics")
    p.add_argument("--interactive-reserve", type=int, default=0,
                   help="slots under --saturate-after-n reserved for "
                        "interactive requests: batch sheds this many slots "
                        "early (class-aware admission, docs/failure-"
                        "handling.md)")
    p.add_argument("--interactive-slo-degrade-ms", type=float, default=0.0,
                   help="inflate every interactive request's REPORTED "
                        "TTFT/ITL by this many ms (SLO records + "
                        "vllm:interactive_*_p99_ms gauges) — models an "
                        "engine failing its interactive SLO for "
                        "latency_protect / class-routing tests")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="inter-token p99 the synthetic SLO terminal records "
                        "report (default: the stream's real pacing) — set "
                        "above the router's --slo-itl-ms to drive its "
                        "violation counters")
    p.add_argument("--compile-stall-ms", type=float, default=0.0,
                   help="stall the FIRST generation this many ms and record "
                        "a flight-recorder compile event (models a cold "
                        "XLA compile)")
    p.add_argument("--flight-dump-dir", type=str, default=None,
                   help="arm flight-recorder anomaly dumps (SIGTERM drain, "
                        "shed bursts) into this directory")
    p.add_argument("--kv-directory-url", type=str, default=None,
                   help="fleet-wide KV directory (cache server) to register "
                        "with and publish deterministic per-prompt chunk "
                        "hashes to on stream completion (router-v2 e2e "
                        "without TPUs)")
    p.add_argument("--migration", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="serve the live-sequence-migration endpoints "
                        "(/migrate_out /migrate_in /migrate_attach "
                        "/migratable) in the real wire shapes "
                        "(docs/migration.md); --no-migration disables")
    p.add_argument("--warm-prefetch-on-boot", type=int, default=0,
                   help="pull this many top fleet-warm chunk hashes "
                        "(dir_top_prefixes) at startup and count warm "
                        "prefix hits against them; needs --kv-directory-url")
    p.add_argument("--fabric", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="run the peer-to-peer KV fabric emulation "
                        "(docs/kv-fabric.md): a real-wire-shape fabric "
                        "listener, GET /kv_fabric advert, and directory-"
                        "driven cross-engine pulls when --kv-directory-url "
                        "is set")
    p.add_argument("--fabric-fail-rate", type=float, default=0.0,
                   help="probability each fabric op replies with an error "
                        "(peers count fallbacks)")
    p.add_argument("--fabric-hang", action="store_true",
                   help="fabric ops stall forever (peer deadline/breaker "
                        "testing)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="advertised serving-mesh tp degree "
                        "(vllm:tensor_parallel_degree on /metrics), so "
                        "router scraping and fleet-capacity math can be "
                        "tested against sharded-engine fleets without TPUs")
    args = p.parse_args()
    app = make_app(
        args.model, args.speed, args.ttft, args.model_label,
        faults={
            "fail_rate": args.fail_rate,
            "fail_first_n": args.fail_first_n,
            "fail_after_chunks": args.fail_after_chunks,
            "hang": args.hang,
            "hang_after_chunks": args.hang_after_chunks,
            "saturate_after_n": args.saturate_after_n,
            "shed_rate": args.shed_rate,
            "retry_after": args.retry_after,
            "crash_after_n": args.crash_after_n,
            "restart_restore_pages": args.restart_restore_pages,
            "slo_itl_ms": args.slo_itl_ms,
            "interactive_reserve": args.interactive_reserve,
            "interactive_slo_degrade_ms": args.interactive_slo_degrade_ms,
            "compile_stall_ms": args.compile_stall_ms,
            "flight_dump_dir": args.flight_dump_dir,
            "kv_directory_url": args.kv_directory_url,
            "migration": args.migration,
            "warm_prefetch_on_boot": args.warm_prefetch_on_boot,
            "fabric": args.fabric,
            "fabric_fail_rate": args.fabric_fail_rate,
            "fabric_hang": args.fabric_hang,
            "tensor_parallel": args.tensor_parallel,
            "self_url": f"http://127.0.0.1:{args.port}",
        },
    )
    asyncio.run(_serve_until_sigterm(app, args.port))


if __name__ == "__main__":
    main()
