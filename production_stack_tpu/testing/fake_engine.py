"""Fake TPU engine: an OpenAI-API mock for router/stack testing with zero
accelerators — the keystone test fixture.

Parity: src/tests/perftest/fake-openai-server.py:1-170 in /root/reference
(streams tokens at --speed with injectable --ttft, tracks running requests),
extended with /metrics in the engine's vllm:* format, sleep/wake, and optional
kv-transfer query params so disaggregated-prefill flows are testable.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid

from aiohttp import web

STATE = {
    "running": 0,
    "total": 0,
    "sleeping": False,
}


def make_app(model: str, speed: float, ttft: float, model_label: str | None = None):
    async def health(request):
        return web.Response(text="")

    async def models(request):
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": model,
                        "object": "model",
                        "created": int(time.time()),
                        "owned_by": "fake-engine",
                    }
                ],
            }
        )

    async def metrics(request):
        text = (
            f'vllm:num_requests_running{{model_name="{model}"}} {STATE["running"]}\n'
            f'vllm:num_requests_waiting{{model_name="{model}"}} 0\n'
            f'vllm:gpu_cache_usage_perc{{model_name="{model}"}} 0.42\n'
            f'vllm:gpu_prefix_cache_hits_total{{model_name="{model}"}} 10\n'
            f'vllm:gpu_prefix_cache_queries_total{{model_name="{model}"}} 20\n'
        )
        return web.Response(text=text, content_type="text/plain")

    async def completions(request):
        return await _generate(request, chat=False)

    async def chat(request):
        return await _generate(request, chat=True)

    async def _generate(request, chat: bool):
        if STATE["sleeping"]:
            return web.json_response({"error": "sleeping"}, status=503)
        body = await request.json()
        max_tokens = int(body.get("max_tokens", 16))
        stream = bool(body.get("stream", False))
        req_id = request.headers.get("X-Request-Id", uuid.uuid4().hex)
        uid = request.headers.get("x-user-id")
        if uid:
            # visible marker for tests asserting user-id header propagation
            print(f"x-user-id={uid}", flush=True)
        STATE["running"] += 1
        STATE["total"] += 1
        created = int(time.time())
        oid = ("chatcmpl-" if chat else "cmpl-") + req_id
        try:
            await asyncio.sleep(ttft)
            if not stream:
                await asyncio.sleep(max_tokens / speed)
                text = "Hello " * max_tokens
                choice = (
                    {"index": 0, "message": {"role": "assistant", "content": text},
                     "finish_reason": "length"}
                    if chat
                    else {"index": 0, "text": text, "finish_reason": "length"}
                )
                return web.json_response(
                    {
                        "id": oid, "object": "chat.completion" if chat else "text_completion",
                        "created": created, "model": model, "choices": [choice],
                        "usage": {
                            "prompt_tokens": 10, "completion_tokens": max_tokens,
                            "total_tokens": 10 + max_tokens,
                        },
                    },
                    headers={"X-Request-Id": req_id},
                )
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream", "X-Request-Id": req_id}
            )
            await resp.prepare(request)
            for i in range(max_tokens):
                delta = {"content": "Hello "} if chat else None
                choice = (
                    {"index": 0, "delta": delta, "finish_reason": None}
                    if chat
                    else {"index": 0, "text": "Hello ", "finish_reason": None}
                )
                await resp.write(
                    f"data: {json.dumps({'id': oid, 'object': 'chat.completion.chunk' if chat else 'text_completion', 'created': created, 'model': model, 'choices': [choice]})}\n\n".encode()
                )
                await asyncio.sleep(1.0 / speed)
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        finally:
            STATE["running"] -= 1

    async def sleep(request):
        STATE["sleeping"] = True
        return web.Response(text="")

    async def wake_up(request):
        STATE["sleeping"] = False
        return web.Response(text="")

    async def is_sleeping(request):
        return web.json_response({"is_sleeping": STATE["sleeping"]})

    async def tokenize(request):
        body = await request.json()
        text = body.get("prompt", "")
        return web.json_response(
            {"tokens": list(text.encode()), "count": len(text.encode()), "max_model_len": 4096}
        )

    app = web.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/sleep", sleep)
    app.router.add_post("/wake_up", wake_up)
    app.router.add_get("/is_sleeping", is_sleeping)
    app.router.add_post("/tokenize", tokenize)
    return app


def main():
    p = argparse.ArgumentParser("fake-tpu-engine")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--model", default="fake/model")
    p.add_argument("--speed", type=float, default=100.0, help="tokens per second")
    p.add_argument("--ttft", type=float, default=0.0, help="injected TTFT seconds")
    p.add_argument("--model-label", default=None)
    args = p.parse_args()
    web.run_app(
        make_app(args.model, args.speed, args.ttft, args.model_label),
        port=args.port, print=None,
    )


if __name__ == "__main__":
    main()
