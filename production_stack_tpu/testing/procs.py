"""Helpers to launch stack components as subprocesses for e2e tests.

Mirrors the reference's test strategy (SURVEY.md §4.2): real HTTP servers on
localhost ports, no cluster, CPU-only JAX.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

import requests

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def cpu_env(extra: dict | None = None) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONPATH", REPO_ROOT)
    if extra:
        env.update(extra)
    return env


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def start_proc(argv: list[str], extra_env: dict | None = None) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable] + argv,
        env=cpu_env(extra_env),
        cwd=REPO_ROOT,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def wait_healthy(url: str, proc: subprocess.Popen, timeout: float = 90.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read() if proc.stdout else ""
            raise RuntimeError(f"process died (rc={proc.returncode}):\n{out[-4000:]}")
        try:
            if requests.get(url, timeout=2).status_code == 200:
                return
        except requests.RequestException:
            pass
        time.sleep(0.3)
    proc.kill()
    raise TimeoutError(f"{url} not healthy after {timeout}s")


def stop_proc(proc: subprocess.Popen) -> str:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=5)
    return proc.stdout.read() if proc.stdout else ""
