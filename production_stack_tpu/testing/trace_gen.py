"""Deterministic trace-driven workload generator (docs/failure-handling.md
priority classes; bench.py --qa trace phase, chaos mixed-class-overload).

Synthesizes the arrival process the multi-tenant SLO work is judged under:

- **bursty + diurnal arrivals** — a non-homogeneous Poisson process whose
  rate is ``base_qps`` modulated by a slow sinusoid (the diurnal swell) with
  periodic multiplicative bursts on top (the thundering herd). Sampled by
  thinning, so the arrival pattern is exact for the composed rate function.
- **mixed context lengths** — log-uniform over [min_context, max_context]
  (default 1k..32k): most requests are short, the tail is genuinely long,
  matching production context distributions better than uniform draws.
- **mixed SLO classes** — each request is ``batch`` with probability
  ``batch_fraction`` else ``interactive``; batch requests draw longer
  outputs (they are the migration/preemption victims under overload).

Everything is driven by one ``random.Random(seed)``: the same arguments
always produce the identical trace (tests/test_slo_classes.py pins this),
which is what makes overload benchmarks comparable across runs — the
variance-bounded QA headline replays the same trace, not a fresh sample.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass


@dataclass
class TraceRequest:
    """One synthetic arrival."""

    t: float             # arrival offset in seconds from trace start
    prompt_tokens: int   # context length
    output_tokens: int   # decode length
    priority: str        # "interactive" | "batch"


def generate_trace(
    *,
    seed: int,
    duration_s: float,
    base_qps: float,
    burst_factor: float = 3.0,
    burst_period_s: float = 30.0,
    burst_duration_s: float = 5.0,
    diurnal_period_s: float = 120.0,
    diurnal_amplitude: float = 0.5,
    batch_fraction: float = 0.3,
    min_context: int = 1024,
    max_context: int = 32768,
    interactive_output: tuple = (16, 128),
    batch_output: tuple = (64, 512),
) -> list:
    """Build the full trace up front (bounded: duration * peak rate).

    Returns ``TraceRequest`` rows sorted by arrival time. Deterministic in
    every argument; no global RNG state is touched.
    """
    if duration_s <= 0 or base_qps <= 0:
        return []
    rng = random.Random(seed)
    amp = max(0.0, min(1.0, diurnal_amplitude))
    burst = max(1.0, burst_factor)

    def rate(t: float) -> float:
        r = base_qps * (
            1.0 + amp * math.sin(2.0 * math.pi * t / diurnal_period_s)
        )
        if burst_period_s > 0 and (t % burst_period_s) < burst_duration_s:
            r *= burst
        return r

    peak = base_qps * (1.0 + amp) * burst
    out: list = []
    t = 0.0
    ln_min, ln_max = math.log(max(1, min_context)), math.log(max_context)
    while True:
        # thinning: propose at the peak rate, accept at rate(t)/peak
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.random() > rate(t) / peak:
            continue
        if rng.random() < batch_fraction:
            priority, (lo, hi) = "batch", batch_output
        else:
            priority, (lo, hi) = "interactive", interactive_output
        out.append(TraceRequest(
            t=round(t, 6),
            prompt_tokens=int(math.exp(rng.uniform(ln_min, ln_max))),
            output_tokens=rng.randint(lo, hi),
            priority=priority,
        ))
    return out


def trace_summary(trace: list) -> dict:
    """Shape digest for logs and assertions (bench embeds it in results)."""
    if not trace:
        return {"n": 0}
    by_class = {"interactive": 0, "batch": 0}
    for r in trace:
        by_class[r.priority] += 1
    ctx = sorted(r.prompt_tokens for r in trace)
    return {
        "n": len(trace),
        "duration_s": round(trace[-1].t, 3),
        "by_class": by_class,
        "context_p50": ctx[len(ctx) // 2],
        "context_max": ctx[-1],
        "mean_qps": round(len(trace) / max(1e-9, trace[-1].t), 3),
    }


__all__ = ["TraceRequest", "generate_trace", "trace_summary"]
