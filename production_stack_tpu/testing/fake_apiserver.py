"""Fake Kubernetes apiserver for operator tests.

In-memory implementation of the REST subset the C++ operator uses:
list/get/create/update/delete on namespaced resources (any group), the
``/status`` subresource, labelSelector filtering, and a line-delimited watch.
This is the stack's envtest analogue (reference: operator
suite_test.go:31-88 spins a real kube-apiserver via envtest; we fake it —
same test purpose, zero cluster).
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import re
from typing import Optional

from aiohttp import web

_COUNTER = itertools.count(1)


class FakeAPIServer:
    def __init__(self):
        # store[(group, version, ns, plural)][name] = object
        self.store: dict[tuple, dict[str, dict]] = {}
        self.watchers: list[tuple[tuple, asyncio.Queue]] = []

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _key(group: str, version: str, ns: str, plural: str) -> tuple:
        return (group, version, ns, plural)

    def _notify(self, key: tuple, etype: str, obj: dict) -> None:
        for wkey, q in self.watchers:
            if wkey == key:
                q.put_nowait({"type": etype, "object": obj})

    @staticmethod
    def _match_selector(obj: dict, selector: Optional[str]) -> bool:
        if not selector:
            return True
        labels = obj.get("metadata", {}).get("labels", {}) or {}
        for clause in selector.split(","):
            if "=" in clause:
                k, v = clause.split("=", 1)
                if labels.get(k.strip()) != v.strip():
                    return False
        return True

    # -- handlers -------------------------------------------------------------

    async def handle(self, request: web.Request) -> web.StreamResponse:
        m = re.match(
            r"^/(?:apis/(?P<group>[^/]+)/|api/)(?P<version>[^/]+)"
            r"(?:/namespaces/(?P<ns>[^/]+))?/(?P<plural>[^/]+)"
            r"(?:/(?P<name>[^/]+))?(?:/(?P<sub>status))?$",
            request.path,
        )
        if not m:
            return web.json_response({"kind": "Status", "code": 404}, status=404)
        group = m.group("group") or ""
        key = self._key(group, m.group("version"), m.group("ns") or "", m.group("plural"))
        name, sub = m.group("name"), m.group("sub")
        coll = self.store.setdefault(key, {})

        if request.method == "GET" and name is None:
            if request.query.get("watch") in ("true", "1"):
                return await self._watch(request, key)
            selector = request.query.get("labelSelector")
            items = [o for o in coll.values() if self._match_selector(o, selector)]
            return web.json_response(
                {
                    "kind": "List",
                    "apiVersion": "v1",
                    "metadata": {"resourceVersion": str(next(_COUNTER))},
                    "items": items,
                }
            )
        if request.method == "GET":
            obj = coll.get(name)
            if obj is None:
                return web.json_response({"kind": "Status", "code": 404}, status=404)
            return web.json_response(obj)
        if request.method == "POST":
            obj = await request.json()
            oname = obj.get("metadata", {}).get("name")
            if not oname:
                return web.json_response({"error": "no name"}, status=400)
            if oname in coll:
                return web.json_response({"kind": "Status", "code": 409}, status=409)
            obj.setdefault("metadata", {})["uid"] = f"uid-{next(_COUNTER)}"
            obj["metadata"]["resourceVersion"] = str(next(_COUNTER))
            coll[oname] = obj
            self._notify(key, "ADDED", obj)
            return web.json_response(obj, status=201)
        if request.method == "PUT":
            obj = await request.json()
            if name not in coll:
                return web.json_response({"kind": "Status", "code": 404}, status=404)
            if sub == "status":
                coll[name]["status"] = obj.get("status", {})
                coll[name]["metadata"]["resourceVersion"] = str(next(_COUNTER))
                return web.json_response(coll[name])
            obj.setdefault("metadata", {})["uid"] = coll[name]["metadata"].get("uid")
            obj["metadata"]["resourceVersion"] = str(next(_COUNTER))
            # deletionTimestamp is apiserver-owned: carry it across updates
            prior_dts = coll[name]["metadata"].get("deletionTimestamp")
            if prior_dts and "deletionTimestamp" not in obj["metadata"]:
                obj["metadata"]["deletionTimestamp"] = prior_dts
            # preserve status across spec updates (K8s semantics)
            if "status" in coll[name] and "status" not in obj:
                obj["status"] = coll[name]["status"]
            # a terminating object whose last finalizer was removed goes away
            # (K8s finalizer semantics — what the real apiserver does when a
            # controller finishes cleanup and clears its finalizer)
            if obj["metadata"].get("deletionTimestamp") and not obj[
                "metadata"
            ].get("finalizers"):
                coll.pop(name, None)
                self._notify(key, "DELETED", obj)
                return web.json_response(obj)
            coll[name] = obj
            self._notify(key, "MODIFIED", obj)
            return web.json_response(obj)
        if request.method == "DELETE":
            obj = coll.get(name)
            if obj is None:
                return web.json_response({"kind": "Status", "code": 404}, status=404)
            if obj.get("metadata", {}).get("finalizers"):
                # finalizer semantics: mark terminating, keep the object until
                # a controller clears its finalizer (K8s graceful deletion)
                if not obj["metadata"].get("deletionTimestamp"):
                    obj["metadata"]["deletionTimestamp"] = "2026-01-01T00:00:00Z"
                    obj["metadata"]["resourceVersion"] = str(next(_COUNTER))
                    self._notify(key, "MODIFIED", obj)
                return web.json_response(obj)
            coll.pop(name, None)
            self._notify(key, "DELETED", obj)
            return web.json_response({"kind": "Status", "code": 200})
        return web.json_response({"kind": "Status", "code": 405}, status=405)

    async def _watch(self, request: web.Request, key: tuple) -> web.StreamResponse:
        resp = web.StreamResponse(
            headers={"Content-Type": "application/json", "Transfer-Encoding": "chunked"}
        )
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        self.watchers.append((key, q))
        try:
            while True:
                event = await q.get()
                await resp.write((json.dumps(event) + "\n").encode())
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self.watchers.remove((key, q))
        return resp


def make_app() -> tuple[web.Application, FakeAPIServer]:
    srv = FakeAPIServer()
    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", srv.handle)
    return app, srv


def main():
    p = argparse.ArgumentParser("fake-apiserver")
    p.add_argument("--port", type=int, required=True)
    args = p.parse_args()
    app, _ = make_app()
    web.run_app(app, port=args.port, print=None)


if __name__ == "__main__":
    main()
