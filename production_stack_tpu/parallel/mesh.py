"""Device-mesh construction for serving.

Axis convention (fixed names, used by every sharding rule in the stack):

- ``dp``: data / replica parallelism — independent request batches. Router-level
  DP (N engine pods) is above this; in-engine dp shards one engine's batch.
- ``tp``: tensor parallelism over ICI within a slice (the reference's
  ``--tensor-parallel-size``, helm deployment-vllm-multi.yaml:149-151 — here
  executed by XLA collectives instead of NCCL).
- ``sp``: sequence/context parallelism (ring attention) — absent in the
  reference (SURVEY.md §2.3), first-class here.
- ``ep``: expert parallelism for MoE models.

- ``pp``: pipeline parallelism — the layer stack shards into contiguous
  stages over this axis and microbatched activations relay stage-to-stage via
  ``lax.ppermute`` (models/llama.py pp path). Outermost so stages can span
  hosts over DCN (the reference's Ray-orchestrated
  ``--pipeline-parallel-size``, ray-cluster.yaml:560-566 — here one SPMD
  program, no Ray). The standalone ``parallel.pipeline`` module holds the
  generic schedule used by the serving path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("pp", "dp", "sp", "ep", "tp")


def make_mesh(
    tp: int = 1,
    dp: int = 1,
    sp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh with axes (pp, dp, sp, ep, tp).

    ``tp`` is the innermost (fastest-varying) axis so tensor-parallel
    collectives ride neighbouring ICI links; ``dp``/``pp`` are outermost so
    replicas and pipeline stages can span hosts over DCN.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = pp * dp * sp * ep * tp
    if need > len(devices):
        raise ValueError(
            f"mesh {pp}x{dp}x{sp}x{ep}x{tp} needs {need} devices, have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(pp, dp, sp, ep, tp)
    return Mesh(arr, AXES)


def single_device_mesh() -> Mesh:
    return make_mesh()
