"""shard_map compatibility across the jax versions this stack deploys on.

The serving tree targets the modern API (``jax.shard_map`` with
``axis_names=``/``check_vma=``, introduced around jax 0.6), but the baked
container toolchain pins jax 0.4.x where the same machinery lives at
``jax.experimental.shard_map.shard_map`` with the complementary calling
convention: partial-manual regions are expressed as ``auto=<unmapped axes>``
instead of ``axis_names=<mapped axes>``, and replication checking is
``check_rep=`` instead of ``check_vma=``. Every sharded entry point
(ring attention, the layer pipeline, the sharded ragged decode kernel) calls
through this module so the version split lives in exactly one place.
"""

from __future__ import annotations

from typing import Optional

import jax

_HAS_NEW_API = hasattr(jax, "shard_map")

# Partial-manual regions (only SOME mesh axes mapped, the rest flowing
# through GSPMD automatically) exist on old jax as shard_map's ``auto=``
# parameter, but on this toolchain they raise NotImplementedError eagerly
# and fatally CHECK-fail XLA's SPMD partitioner under jit — unusable either
# way. Callers branch on this flag: with partial manual unavailable they map
# EVERY axis and leave the would-be-auto axes out of their specs, which
# shard_map's boundary resharding turns into replicated (redundant) compute
# along those axes — numerically identical, and the unmapped axes are size 1
# in every tier-1 serving config that reaches these paths.
PARTIAL_MANUAL = _HAS_NEW_API


def shard_map(
    f,
    mesh,
    *,
    in_specs,
    out_specs,
    axis_names: Optional[set] = None,
    check: bool = False,
):
    """Version-bridging ``shard_map``.

    ``axis_names`` is the MODERN meaning: the mesh axes the body is manual
    over (None = all of them). Old jax cannot do partial-manual (see
    PARTIAL_MANUAL above), so there the region is widened to full-manual:
    axes absent from a spec then mean "replicated into every shard" rather
    than "GSPMD-managed", which computes redundantly along them but returns
    the same values.
    """
    if _HAS_NEW_API:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check, **kw,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check,
    )


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis, from inside a shard_map body."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax._src.core import axis_frame  # old jax: returns the size itself

    sz = axis_frame(axis_name)
    return sz if isinstance(sz, int) else sz.size


def current_manual_axes() -> tuple[set, Optional[object]]:
    """(axes already Manual in the current trace context, the context mesh).

    Modern jax exposes this as ``jax.sharding.get_abstract_mesh()`` — a
    nested shard_map inside a manual region must be built against that
    abstract mesh, not the concrete one. Old jax has no public probe; the
    serving paths that nest (the decode kernel inside the pp pipeline's
    manual region) are TPU-only there, and the single-level regions tier-1
    exercises never need it — so (empty, None) is the correct degradation.
    """
    try:
        ctx = jax.sharding.get_abstract_mesh()
    except AttributeError:
        return set(), None
    if ctx is None or ctx.empty:
        return set(), None
    return set(ctx.manual_axes), ctx
