"""Ring attention: sequence-parallel exact attention over the ``sp`` mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §2.3: "No ring
attention / Ulysses / context parallel anywhere in the tree") but the TPU
build treats as first-class: when one sequence's KV exceeds a chip's HBM, the
sequence is sharded over ``sp`` and KV blocks rotate around the ring via
``lax.ppermute`` while every device accumulates online-softmax partials for
its local queries. Compute and the KV transfer for the *next* step overlap
(XLA schedules the ppermute concurrently with the attention matmuls), so at
the steady state the ring adds no wall-clock over local attention — the
blockwise-parallel / ring-attention construction (Liu et al.; PAPERS.md).

All collectives are XLA ``ppermute`` over ICI neighbours — no NCCL, no
point-to-point runtime (the reference's NIXL/Ray have no role here).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.parallel import compat

NEG_INF = -1e30


def _online_block(qf, k, v, visible, m, l, acc):
    """One online-softmax accumulation step of q against a KV block.

    qf:      [B, T, KH, G, D] f32 (pre-scaled)
    k, v:    [B, S, KH, D]
    visible: [B, T, S] bool
    m, l:    [B, T, KH, G] f32 running max / denominator
    acc:     [B, T, KH, G, D] f32 running numerator
    """
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->btkgs", qf, kf)
    scores = jnp.where(visible[:, :, None, None, :], scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(visible[:, :, None, None, :], p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "btkgs,bskd->btkgd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def ring_attention_local(
    q: jnp.ndarray,            # [B, Tl, NH, D] local query shard
    k: jnp.ndarray,            # [B, Sl, KH, D] local KV shard
    v: jnp.ndarray,            # [B, Sl, KH, D]
    q_positions: jnp.ndarray,  # [B, Tl] global positions; -1 = padding
    kv_lens: jnp.ndarray | None,  # [B] global valid KV length (offset mode)
    kv_positions: jnp.ndarray | None = None,  # [B, Sl] explicit positions
    *,
    axis_name: str = "sp",
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Per-shard body — call inside shard_map/pjit over ``axis_name``.

    Device i initially holds KV block i (global offset i*Sl). Each of the
    ``sp`` steps attends local queries to the currently-held block, then
    rotates the block to the next ring neighbour.

    With ``kv_positions`` the block's global positions are explicit (slots
    with position -1 are invisible) and rotate around the ring alongside K/V —
    the serving path uses this because page-pool gathers interleave stale
    pool slots and in-register chunk K/V, so slot index != global position.
    """
    B, Tl, NH, D = q.shape
    Sl, KH = k.shape[1], k.shape[2]
    G = NH // KH
    scale = sm_scale if sm_scale is not None else D**-0.5
    sp = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    qf = (q.astype(jnp.float32) * scale).reshape(B, Tl, KH, G, D)
    m = jnp.full((B, Tl, KH, G), NEG_INF, jnp.float32)
    l = jnp.zeros((B, Tl, KH, G), jnp.float32)
    acc = jnp.zeros((B, Tl, KH, G, D), jnp.float32)

    def step(carry, step_idx):
        m, l, acc, k, v, kvp = carry
        if kvp is not None:
            # explicit-position semantics match flash_attention's: a slot is
            # visible iff its position is valid (>= 0) and causal; kv_lens is
            # not consulted (invalid slots carry -1)
            visible = (kvp[:, None, :] <= q_positions[:, :, None]) & (
                kvp[:, None, :] >= 0
            )
        else:
            src = (my - step_idx) % sp      # who this block belongs to
            offset = src * Sl               # its global position offset
            idx = offset + jnp.arange(Sl)
            visible = (idx[None, None, :] <= q_positions[:, :, None]) & (
                idx[None, None, :] < kv_lens[:, None, None]
            )
        m, l, acc = _online_block(qf, k, v, visible, m, l, acc)
        # rotate the KV block while the next step's math is scheduled
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if kvp is not None:
            kvp = lax.ppermute(kvp, axis_name, perm)
        return (m, l, acc, k, v, kvp), None

    (m, l, acc, _, _, _), _ = lax.scan(
        step, (m, l, acc, k, v, kv_positions), jnp.arange(sp)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(B, Tl, NH, D).astype(q.dtype)


def ring_attention_serving(
    mesh: Mesh,
    q: jnp.ndarray,            # [B, T, NH, D] prefill chunk queries
    k: jnp.ndarray,            # [B, S, KH, D] gathered pool + chunk KV
    v: jnp.ndarray,
    q_positions: jnp.ndarray,  # [B, T] global positions, -1 pad
    kv_positions: jnp.ndarray,  # [B, S] per-slot global positions, -1 invalid
    *,
    axis_name: str = "sp",
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Sequence-parallel prefill attention inside the jitted serving step.

    Visibility comes from ``kv_positions`` alone (slot visible iff position
    >= 0 and <= query position) — matching flash_attention's explicit-
    positions semantics, which ignore kv_lens.

    Partial-manual shard_map (modern jax): only ``sp`` is mapped — dp/tp
    shardings of the batch/head axes keep flowing through GSPMD
    automatically, so this composes with tensor parallelism without explicit
    specs. On old jax (no partial manual: compat.PARTIAL_MANUAL False) the
    region widens to full-manual, and there an axis that is mapped but
    UNMENTIONED in the specs miscompiles when the shard_map sits inside the
    layer ``lax.scan`` (observed: tp-replicated specs inside the scan
    returned garbage attention on an sp x tp mesh — the serving engine's
    exact shape). So on old jax the data axes are mapped EXPLICITLY instead:
    batch over ``dp`` and heads over ``tp`` (each shard ring-attends its own
    head slice — also no redundant compute). Head-over-tp sharding needs
    NH/KH divisible by tp; callers (models/llama.py) fall back to the GSPMD
    flash path otherwise. T and S pad up to multiples of sp (padded KV slots
    get position -1 => invisible; padded queries get position -1 =>
    discarded rows).
    """
    sp = mesh.shape[axis_name]
    B, T = q.shape[:2]
    S = k.shape[1]
    pad_t, pad_s = (-T) % sp, (-S) % sp
    if pad_t:
        q = jnp.pad(q, ((0, 0), (0, pad_t), (0, 0), (0, 0)))
        q_positions = jnp.pad(q_positions, ((0, 0), (0, pad_t)), constant_values=-1)
    if pad_s:
        k = jnp.pad(k, ((0, 0), (0, pad_s), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_s), (0, 0), (0, 0)))
        kv_positions = jnp.pad(
            kv_positions, ((0, 0), (0, pad_s)), constant_values=-1
        )
    def fn(q, k, v, q_positions, kv_positions):
        return ring_attention_local(
            q, k, v, q_positions, None, kv_positions,
            axis_name=axis_name, sm_scale=sm_scale,
        )

    # when nested inside another partial-manual shard_map (e.g. the pp layer
    # pipeline), the context mesh is an AbstractMesh with that axis already
    # Manual — shard_map requires the matching mesh object, not the concrete
    # one we were constructed with
    _, ctx = compat.current_manual_axes()
    if ctx is not None:
        mesh = ctx
    manual = {axis_name}
    batch_ax = head_ax = None
    if not compat.PARTIAL_MANUAL:
        # full-manual widening (old jax): map the batch/head data axes
        # explicitly — see the docstring; a mapped-but-unmentioned axis
        # inside the layer scan is exactly the miscompile this avoids
        names = set(mesh.axis_names)
        if "dp" in names:
            manual.add("dp")
            batch_ax = "dp"
        if "tp" in names:
            NH, KH = q.shape[2], k.shape[2]
            tp = mesh.shape["tp"]
            if NH % tp or KH % tp:
                raise ValueError(
                    f"ring attention with tp={tp} needs head counts "
                    f"divisible by tp (NH={NH}, KH={KH}); use the GSPMD "
                    "attention path instead"
                )
            manual.add("tp")
            head_ax = "tp"
        # any OTHER >1 axis (ep, pp) has no natural attention dim to map —
        # it would be mapped-but-unmentioned, the documented miscompile.
        # Refuse loudly; callers (models/llama.py ring gate) fall back to
        # the GSPMD flash path on such meshes.
        unmappable = [
            a for a in names - {axis_name, "dp", "tp"}
            if mesh.shape[a] > 1
        ]
        if unmappable:
            raise ValueError(
                f"ring attention cannot widen to full-manual over "
                f"{sorted(unmappable)} on this jax version; use the GSPMD "
                "attention path instead"
            )
    seq = P(batch_ax, axis_name, head_ax, None)
    pos_spec = P(batch_ax, axis_name)
    out = compat.shard_map(
        fn,
        mesh,
        axis_names=manual,
        in_specs=(seq, seq, seq, pos_spec, pos_spec),
        out_specs=seq,
        check=False,
    )(q, k, v, q_positions, kv_positions)
    return out[:, :T]


def ring_attention(
    mesh: Mesh,
    q: jnp.ndarray,            # [B, T, NH, D] global
    k: jnp.ndarray,            # [B, S, KH, D] global
    v: jnp.ndarray,
    q_positions: jnp.ndarray,  # [B, T]
    kv_lens: jnp.ndarray,      # [B]
    *,
    axis_name: str = "sp",
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Convenience wrapper: shard T/S over ``axis_name`` (heads over ``tp`` if
    the mesh has it) and run the ring. Output sharding matches q."""
    head_axis = "tp" if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 else None
    qspec = P(None, axis_name, head_axis, None)
    kvspec = P(None, axis_name, head_axis, None)
    fn = functools.partial(
        ring_attention_local, axis_name=axis_name, sm_scale=sm_scale
    )
    shard_fn = compat.shard_map(
        fn,
        mesh,
        in_specs=(qspec, kvspec, kvspec, P(None, axis_name), P(None)),
        out_specs=qspec,
        check=False,
    )
    return shard_fn(q, k, v, q_positions, kv_lens)
