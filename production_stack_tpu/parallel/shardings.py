"""Sharding rules (GSPMD PartitionSpecs) for model parameters, KV page pools,
and per-step batch inputs.

Megatron-style tensor parallelism expressed declaratively: column-parallel
projections shard their output dim on ``tp``, row-parallel shard their input
dim; XLA inserts the (reduce-scatter/all-reduce) collectives. No NCCL —
this is the TPU replacement for the reference's in-engine TP
(SURVEY.md §2.3: "jax.sharding/pjit mesh over ICI within a slice").

KV page pools shard the *kv-head* axis on ``tp`` so each chip holds only its
heads' pages — the paged-attention gather then never crosses chips.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Llama-family parameter tree -> PartitionSpec (leading None = stacked layer axis).
LLAMA_PARAM_SPECS = {
    "embed": P("tp", None),            # vocab-sharded; GSPMD handles the gather
    "layers": {
        "attn_norm": P(None, None),
        "wq": P(None, None, "tp"),     # column parallel
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),     # row parallel
        "mlp_norm": P(None, None),
        "w_gate": P(None, None, "tp"),
        "w_up": P(None, None, "tp"),
        "w_down": P(None, "tp", None),
    },
    "final_norm": P(None),
    "lm_head": P(None, "tp"),
}

# [L, P, page_size, KH, D] pools: shard kv heads over tp.
KV_PAGES_SPEC = P(None, None, None, "tp", None)

BATCH_SPECS = {
    "input_ids": P("dp", None),
    "positions": P("dp", None),
    "page_table": P("dp", None),
    "kv_lens": P("dp"),
    "logits": P("dp", "tp"),
}


def param_specs_for(params: dict) -> dict:
    """LLAMA_PARAM_SPECS restricted to the keys present (tied embeddings drop
    lm_head)."""
    specs = {k: v for k, v in LLAMA_PARAM_SPECS.items() if k in params}
    return specs


def shard_tree(tree, specs, mesh: Mesh):
    """Device_put a pytree with per-leaf PartitionSpecs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: x is None,
    )


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
