"""Sharding rules (GSPMD PartitionSpecs) for model parameters, KV page pools,
and per-step batch inputs.

Megatron-style tensor parallelism expressed declaratively: column-parallel
projections shard their output dim on ``tp``, row-parallel shard their input
dim; XLA inserts the (reduce-scatter/all-reduce) collectives. No NCCL —
this is the TPU replacement for the reference's in-engine TP
(SURVEY.md §2.3: "jax.sharding/pjit mesh over ICI within a slice").

KV page pools shard the *kv-head* axis on ``tp`` so each chip holds only its
heads' pages — the paged-attention gather then never crosses chips.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Per-parameter PartitionSpecs, keyed by leaf name. Top-level leaves are plain
# tensors; leaves under "layers" are layer-stacked and get a leading None for
# the [L] axis prepended by param_specs_for. Covers every model family
# (Llama/Mistral/Qwen2/Mixtral in models/llama.py, OPT in models/opt.py).
_TOP_SPECS = {
    "embed": P("tp", None),            # vocab-sharded; GSPMD handles the gather
    "pos_embed": P(None, None),
    "final_norm": P(None),
    "final_norm_w": P(None),
    "final_norm_b": P(None),
    "lm_head": P(None, "tp"),
}
_LAYER_SPECS = {
    "attn_norm": P(None),
    "attn_norm_w": P(None),
    "attn_norm_b": P(None),
    "wq": P(None, "tp"),               # column parallel (+ bias on the out dim)
    "bq": P("tp"),
    "wk": P(None, "tp"),
    "bk": P("tp"),
    "wv": P(None, "tp"),
    "bv": P("tp"),
    "wo": P("tp", None),               # row parallel (bias after the all-reduce)
    "bo": P(None),
    "mlp_norm": P(None),
    "mlp_norm_w": P(None),
    "mlp_norm_b": P(None),
    "post_attn_norm": P(None),         # Gemma-2 sandwich norms
    "post_mlp_norm": P(None),
    "w_gate": P(None, "tp"),
    "w_up": P(None, "tp"),
    "w_down": P("tp", None),
    "fc1": P(None, "tp"),
    "fc1_b": P("tp"),
    "fc2": P("tp", None),
    "fc2_b": P(None),
    # MoE (Mixtral): experts sharded over ep, each expert's FFN over tp — the
    # contraction over E inserts one psum over the ep axis (expert parallelism).
    "moe_router": P(None, None),
    "moe_gate": P("ep", None, "tp"),
    "moe_up": P("ep", None, "tp"),
    "moe_down": P("ep", "tp", None),
}

# [L, P, page_size, KH, D] pools: shard kv heads over tp.
KV_PAGES_SPEC = P(None, None, None, "tp", None)
# Under pipeline parallelism each stage holds only its own layers' pages.
KV_PAGES_SPEC_PP = P("pp", None, None, "tp", None)

BATCH_SPECS = {
    "input_ids": P("dp", None),
    "positions": P("dp", None),
    "page_table": P("dp", None),
    "kv_lens": P("dp"),
    "logits": P("dp", "tp"),
}


def param_specs_for(params: dict, pp: bool = False) -> dict:
    """PartitionSpec tree matching the structure of `params` (any model
    family), built from the per-leaf-name tables above.

    With ``pp`` the layer-stacked leaves shard their leading [L] axis over the
    ``pp`` mesh axis (each pipeline stage holds a contiguous layer slice);
    embed/lm_head stay replicated so first/last stages need no gathers.
    """
    layer_lead = "pp" if pp else None
    specs: dict = {}
    for k, v in params.items():
        if k == "layers":
            specs[k] = {n: P(layer_lead, *_LAYER_SPECS[n]) for n in v}
        else:
            specs[k] = _TOP_SPECS[k]
    return specs


def shard_tree(tree, specs, mesh: Mesh):
    """Device_put a pytree with per-leaf PartitionSpecs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: x is None,
    )


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
