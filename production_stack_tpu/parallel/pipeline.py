"""Pipeline parallelism over the ``pp`` mesh axis — multi-host stage execution
without Ray.

The reference runs pipeline parallelism by provisioning a KubeRay cluster and
passing ``--pipeline-parallel-size`` to vLLM (/root/reference
helm/templates/ray-cluster.yaml:515-566; tutorials/15-basic-pipeline-parallel.md).
Here PP is a mesh axis: layers shard over ``pp`` (each device holds a
contiguous stage of the layer stack), microbatches flow stage-to-stage via
``lax.ppermute`` over ICI/DCN, and the whole schedule is one jitted SPMD
program — JAX's multi-controller runtime replaces the Ray choreography
(SURVEY.md §7 hard part #4).

Schedule: GPipe-style fill-drain. With M microbatches and S stages the scan
runs M + S - 1 ticks; device s is active on ticks [s, s + M). Bubble fraction
(S-1)/(M+S-1) — callers pick M >= 4*S for serving prefill.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_local(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,  # [M, ...mb shape...] (replicated)
    *,
    axis_name: str = "pp",
):
    """Per-shard GPipe schedule — call inside shard_map over ``axis_name``.

    ``stage_fn(stage_params, x) -> y`` runs this device's slice of the layer
    stack; ``stage_params`` is the local stage's shard (layer axis already
    split by shard_map). Returns the final-stage outputs, [M, ...] on every
    device (psum-broadcast at the end).
    """
    S = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + S - 1
    perm = [(i, i + 1) for i in range(S - 1)]

    buf = jnp.zeros_like(microbatches[0])
    outs = jnp.zeros_like(microbatches)

    def tick(carry, t):
        buf, outs = carry
        mb_idx = jnp.clip(t - s, 0, M - 1)
        active = (t >= s) & (t - s < M)
        # stage 0 injects fresh microbatches; others consume the ppermuted buf
        x_in = jnp.where(s == 0, microbatches[jnp.clip(t, 0, M - 1)], buf)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage records its (active) output
        outs = jnp.where(
            active & (s == S - 1),
            lax.dynamic_update_index_in_dim(outs, y, mb_idx, 0),
            outs,
        )
        # ship activations to the next stage (last stage sends nothing)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(T))
    # broadcast final outputs from the last stage to every device
    outs = lax.psum(jnp.where(s == S - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_forward(
    mesh: Mesh,
    stage_fn: Callable,
    params,                    # pytree; every leaf's leading axis = num layers
    microbatches: jnp.ndarray, # [M, ...]
    *,
    axis_name: str = "pp",
):
    """Shard ``params``' layer axis over ``axis_name`` and run the pipeline.

    ``stage_fn(stage_params, x)`` sees the local ``layers/S``-sized stack —
    typically a ``lax.scan`` over its layers.
    """
    fn = functools.partial(pipeline_local, stage_fn, axis_name=axis_name)
    pspec = jax.tree.map(lambda _: P(axis_name), params)
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return shard_fn(params, microbatches)
