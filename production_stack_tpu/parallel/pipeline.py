"""Pipeline parallelism over the ``pp`` mesh axis — multi-host stage execution
without Ray.

The reference runs pipeline parallelism by provisioning a KubeRay cluster and
passing ``--pipeline-parallel-size`` to vLLM (/root/reference
helm/templates/ray-cluster.yaml:515-566; tutorials/15-basic-pipeline-parallel.md).
Here PP is a mesh axis: layers shard over ``pp`` (each device holds a
contiguous stage of the layer stack), microbatches flow stage-to-stage via
``lax.ppermute`` over ICI/DCN, and the whole schedule is one jitted SPMD
program — JAX's multi-controller runtime replaces the Ray choreography
(SURVEY.md §7 hard part #4).

Schedule: GPipe-style fill-drain. With M microbatches and S stages the scan
runs M + S - 1 ticks; device s is active on ticks [s, s + M). Bubble fraction
(S-1)/(M+S-1) — callers pick M >= 4*S for serving prefill.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.parallel import compat


def pipeline_local(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,  # [M, ...mb shape...] (replicated)
    *,
    axis_name: str = "pp",
):
    """Per-shard GPipe schedule — call inside shard_map over ``axis_name``.

    ``stage_fn(stage_params, x) -> y`` runs this device's slice of the layer
    stack; ``stage_params`` is the local stage's shard (layer axis already
    split by shard_map). Returns the final-stage outputs, [M, ...] on every
    device (psum-broadcast at the end).
    """
    S = compat.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + S - 1
    perm = [(i, i + 1) for i in range(S - 1)]

    buf = jnp.zeros_like(microbatches[0])
    outs = jnp.zeros_like(microbatches)

    def tick(carry, t):
        buf, outs = carry
        mb_idx = jnp.clip(t - s, 0, M - 1)
        active = (t >= s) & (t - s < M)
        # stage 0 injects fresh microbatches; others consume the ppermuted buf
        x_in = jnp.where(s == 0, microbatches[jnp.clip(t, 0, M - 1)], buf)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage records its (active) output
        outs = jnp.where(
            active & (s == S - 1),
            lax.dynamic_update_index_in_dim(outs, y, mb_idx, 0),
            outs,
        )
        # ship activations to the next stage (last stage sends nothing)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(T))
    # broadcast final outputs from the last stage to every device
    outs = lax.psum(jnp.where(s == S - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_forward(
    mesh: Mesh,
    stage_fn: Callable,
    params,                    # pytree; every leaf's leading axis = num layers
    microbatches: jnp.ndarray, # [M, ...]
    *,
    axis_name: str = "pp",
):
    """Shard ``params``' layer axis over ``axis_name`` and run the pipeline.

    ``stage_fn(stage_params, x)`` sees the local ``layers/S``-sized stack —
    typically a ``lax.scan`` over its layers.
    """
    fn = functools.partial(pipeline_local, stage_fn, axis_name=axis_name)
    pspec = jax.tree.map(lambda _: P(axis_name), params)
    shard_fn = compat.shard_map(
        fn,
        mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check=False,
    )
    return shard_fn(params, microbatches)


def serving_layer_pipeline(
    mesh: Mesh,
    layer: Callable,
    x: jnp.ndarray,        # [B, T, H] embedded activations
    aux,                   # pytree of [B, ...] per-sequence tensors
    scan_xs,               # (layers, k_pages, v_pages, lora_layers) - [L, ...]
    *,
    axis_name: str = "pp",
):
    """GPipe schedule for the serving forward: the layer stack (and each
    layer's KV pool pages) shards into contiguous stages over ``axis_name``;
    microbatches over the batch dim relay stage-to-stage via ``ppermute``.

    Partial-manual shard_map: only ``axis_name`` is mapped, so the dp/sp/ep/tp
    GSPMD shardings of activations/params keep flowing automatically inside
    the body — PP composes with TP without explicit specs (the reference
    reaches the same pairing via Ray + vLLM, ray-cluster.yaml:560-566).

    ``layer`` is the model's scan body: ``layer((x, aux), (lp, kp, vp, ll)) ->
    ((x', aux), (k_new, v_new))`` (write-after-attend mode — pools read-only
    inside, per-layer chunk K/V out). Returns (x_final [B, T, H], (k_new,
    v_new) [L, B, T, KH, D] with L sharded over ``axis_name``).
    """
    pp = mesh.shape[axis_name]
    B, T, H = x.shape
    # microbatch count: enough to keep stages busy (bubble (S-1)/(M+S-1)),
    # bounded by the batch; B and pp are powers of two in serving buckets
    M = min(B, 2 * pp)
    while B % M:
        M -= 1
    mb = B // M
    layers, k_pages, v_pages, ll = scan_xs

    def body(x, aux, layers, kp, vp, ll):
        S = compat.axis_size(axis_name)
        s = lax.axis_index(axis_name)
        perm = [(i, i + 1) for i in range(S - 1)]
        KH, D = kp.shape[3], kp.shape[4]
        Ll = jax.tree.leaves(layers)[0].shape[0]
        xs = x.reshape(M, mb, T, H)
        aux_mb = jax.tree.map(lambda a: a.reshape(M, mb, *a.shape[1:]), aux)
        Tt = M + S - 1

        buf = jnp.zeros((mb, T, H), x.dtype)
        outs = jnp.zeros((M, mb, T, H), x.dtype)
        k_out = jnp.zeros((M, Ll, mb, T, KH, D), kp.dtype)
        v_out = jnp.zeros((M, Ll, mb, T, KH, D), vp.dtype)

        def tick(carry, t):
            buf, k_out, v_out, outs = carry
            mb_i = jnp.clip(t - s, 0, M - 1)
            active = (t >= s) & (t - s < M)
            x_in = jnp.where(s == 0, xs[jnp.clip(t, 0, M - 1)], buf)
            a = jax.tree.map(
                lambda z: lax.dynamic_index_in_dim(z, mb_i, 0, keepdims=False),
                aux_mb,
            )
            (y, _), (k_new, v_new) = lax.scan(layer, (x_in, a), (layers, kp, vp, ll))
            y = jnp.where(active, y, jnp.zeros_like(y))
            k_out = jnp.where(
                active,
                lax.dynamic_update_index_in_dim(k_out, k_new, mb_i, 0),
                k_out,
            )
            v_out = jnp.where(
                active,
                lax.dynamic_update_index_in_dim(v_out, v_new, mb_i, 0),
                v_out,
            )
            outs = jnp.where(
                active & (s == S - 1),
                lax.dynamic_update_index_in_dim(outs, y, mb_i, 0),
                outs,
            )
            # relay activations to the next stage (overlaps with next tick).
            # The relay runs in f32: XLA:CPU miscompiles bf16 collectives
            # under partially-manual shard_map (upcast is lossless, and on
            # TPU the extra convert fuses away).
            buf = lax.ppermute(
                y.astype(jnp.float32), axis_name, perm
            ).astype(y.dtype)
            return (buf, k_out, v_out, outs), None

        (_, k_out, v_out, outs), _ = lax.scan(
            tick, (buf, k_out, v_out, outs), jnp.arange(Tt)
        )
        # final activations live on the last stage; broadcast to all (f32:
        # see the relay note above)
        outs = lax.psum(
            jnp.where(s == S - 1, outs.astype(jnp.float32),
                      jnp.zeros(outs.shape, jnp.float32)),
            axis_name,
        ).astype(x.dtype)
        x_final = outs.reshape(B, T, H)
        # [M, Ll, mb, ...] -> [Ll, B, ...] (B split as m*mb + r)
        k_new = k_out.transpose(1, 0, 2, 3, 4, 5).reshape(Ll, B, T, KH, D)
        v_new = v_out.transpose(1, 0, 2, 3, 4, 5).reshape(Ll, B, T, KH, D)
        return x_final, k_new, v_new

    lead = P(axis_name)
    layer_specs = jax.tree.map(lambda _: lead, layers)
    ll_specs = None if ll is None else jax.tree.map(lambda _: lead, ll)
    aux_specs = jax.tree.map(lambda _: P(), aux)
    x_final, k_new, v_new = compat.shard_map(
        body,
        mesh,
        axis_names={axis_name},
        in_specs=(P(), aux_specs, layer_specs, lead, lead, ll_specs),
        out_specs=(P(), lead, lead),
        check=False,
    )(x, aux, layers, k_pages, v_pages, ll)
    return x_final, (k_new, v_new)
