"""production_stack_tpu — a TPU-native LLM serving stack.

A ground-up re-design of the capabilities of the vLLM Production Stack reference
(router + helm + operator + observability around a CUDA serving engine) for TPU:

- ``engine``   — JAX/XLA/Pallas serving engine: paged KV cache in HBM, ragged paged
  attention, continuous batching with shape bucketing, prefix caching, OpenAI API.
- ``models``   — model families (Llama, OPT, Qwen2, Mixtral-style MoE) as pure
  functional JAX, scanned over layers for fast compiles.
- ``ops``      — TPU kernels: RoPE, RMSNorm, paged/flash attention (XLA reference +
  Pallas TPU implementations), sampling.
- ``parallel`` — mesh construction, sharding rules (dp/tp/sp/ep/pp), ring attention
  over ICI, pipeline parallelism, KV transfer between meshes.
- ``router``   — L7 request router: service discovery, round-robin / session /
  prefix-aware / KV-aware / disaggregated-prefill routing, stats, Prometheus metrics.
- ``kvoffload``— tiered KV cache (HBM -> host DRAM -> disk -> remote cache server)
  plus the global KV-index controller used by KV-aware routing.

The reference stack delegates model execution to vLLM; here the engine is first-party
(see SURVEY.md section "Critical framing").
"""

__version__ = "0.1.0"
