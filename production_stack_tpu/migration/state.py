"""Migration wire format: the sealed snapshot document a sequence moves as.

The snapshot is everything a *target* engine needs to resume a running
sequence mid-stream:

- **token history** — ``tokens`` is the full ``prompt + emitted output`` id
  list at freeze time. Position and emitted-token count derive from it
  (``prompt_len`` / ``output_len`` split it), and it doubles as the
  continuation's prompt: the target re-admits it through the ordinary
  prefix-cache path, so shipped KV pages are shared and everything past them
  is *recomputed deterministically* — which is exactly what makes greedy
  continuation bit-identical (same weights, same tokens, same logits).
- **KV page chain** — the hex chunk hashes (the fleet-standard rolling
  blake2b chain, engine/kv_manager.prefix_hashes) of the fully-written pages
  whose blobs were CONFIRMED saved into the offload tiers at freeze time.
  Blobs move through the existing tier/transfer path and are CRC-verified on
  every read (kvoffload/serde.py), exactly like warm-start manifests; a
  missing or corrupt blob truncates the restore there and the tail
  recomputes. Only ``(len(tokens) - 1) // page_size`` pages are ever listed:
  the newest emitted token's KV is not written until it is fed back as the
  next step's input, so the page containing position ``len(tokens) - 1`` is
  not yet complete.
- **sampling/decode state** — the ORIGINAL request's sampling params plus
  the emitted count; :func:`continuation_params` derives the target-side
  params (max_tokens/min_tokens less what was already emitted). Greedy
  (temperature 0) continuation is bit-identical; sampled continuation picks
  up the target's RNG stream (the per-engine RNG key is not portable) and is
  quality-equivalent, not bit-identical — documented in docs/migration.md.
- **presentation metadata** — response id / chat-vs-completion / created /
  client-visible model and prompt token count, so the target can emit
  continuation chunks in the exact client wire shape and the final usage
  block reports whole-request totals.

The document travels as ``seal_bytes`` (versioned header + length + CRC32,
kvoffload/serde.py) so a truncated or bit-flipped snapshot is rejected at
``/migrate_in`` instead of resuming a corrupted stream.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from production_stack_tpu.kvoffload.serde import seal_bytes, unseal_bytes

SNAPSHOT_FORMAT = 1

# SamplingParams fields that ride the wire verbatim (continuation_params
# adjusts the budget fields afterwards)
_PARAM_FIELDS = (
    "max_tokens", "temperature", "top_k", "top_p", "stop", "ignore_eos",
    "min_tokens", "seed", "presence_penalty", "frequency_penalty",
    "repetition_penalty",
)


@dataclasses.dataclass
class SequenceSnapshot:
    request_id: str          # wire id the continuation parks under on the target
    model: str               # engine model name (must match on the target)
    page_size: int           # source KV page size (chunk-hash identity)
    tokens: list             # prompt_ids + output_ids at freeze time
    prompt_len: int          # split point: tokens[:prompt_len] was the prompt
    output_len: int          # emitted tokens (== len(tokens) - prompt_len)
    params: dict             # ORIGINAL SamplingParams fields (_PARAM_FIELDS)
    page_hashes: list        # hex chunk hashes, confirmed-restorable chain prefix
    meta: dict               # presentation: oid/chat/created/client model+usage

    def to_doc(self) -> dict:
        return {"format": SNAPSHOT_FORMAT, **dataclasses.asdict(self)}

    @staticmethod
    def from_doc(doc: dict) -> "SequenceSnapshot":
        if int(doc.get("format", 0)) != SNAPSHOT_FORMAT:
            raise ValueError(
                f"unsupported migration snapshot format {doc.get('format')!r}"
            )
        return SequenceSnapshot(
            request_id=str(doc["request_id"]),
            model=str(doc["model"]),
            page_size=int(doc["page_size"]),
            tokens=[int(t) for t in doc["tokens"]],
            prompt_len=int(doc["prompt_len"]),
            output_len=int(doc["output_len"]),
            params=dict(doc.get("params") or {}),
            page_hashes=[str(h) for h in doc.get("page_hashes") or []],
            meta=dict(doc.get("meta") or {}),
        )


def snapshot_to_wire(snap: SequenceSnapshot) -> bytes:
    """Sealed (CRC-framed) bytes for the POST /migrate_in body."""
    return seal_bytes(json.dumps(snap.to_doc()).encode(), kind="migration")


def snapshot_from_wire(data: bytes) -> SequenceSnapshot:
    """Parse + integrity-verify a /migrate_in body. Raises
    ``KVIntegrityError`` (corrupt/truncated) or ``ValueError`` (malformed)."""
    _, body = unseal_bytes(data)
    doc = json.loads(body)
    if not isinstance(doc, dict):
        raise ValueError("migration snapshot must be a JSON object")
    return SequenceSnapshot.from_doc(doc)


def params_to_doc(params) -> dict:
    """SamplingParams -> wire dict (original request values, unadjusted)."""
    return {f: getattr(params, f) for f in _PARAM_FIELDS}


def continuation_params(snap: SequenceSnapshot):
    """Target-side SamplingParams: budgets shrink by what was emitted.

    The continuation's prompt is ``snap.tokens`` (original prompt + emitted
    output), so ``max_tokens`` / ``min_tokens`` count only the REMAINING
    tokens. Raises ``ValueError`` when nothing remains (the source must not
    migrate a sequence about to finish)."""
    from production_stack_tpu.engine.scheduler import SamplingParams

    p = dict(snap.params)
    remaining = int(p.get("max_tokens", 0)) - snap.output_len
    if remaining < 1:
        raise ValueError(
            f"nothing left to generate (max_tokens {p.get('max_tokens')}, "
            f"already emitted {snap.output_len})"
        )
    p["max_tokens"] = remaining
    p["min_tokens"] = max(0, int(p.get("min_tokens", 0)) - snap.output_len)
    p["stop"] = list(p.get("stop") or [])
    return SamplingParams(**{k: p[k] for k in _PARAM_FIELDS})


def unmigratable_reason(seq) -> Optional[str]:
    """Why a live sequence cannot migrate, or None when it can.

    Restrictions are *semantic*, not plumbing: state the target cannot
    reconstruct faithfully refuses migration instead of silently drifting.
    The controller treats a refusal as "pick another victim"."""
    params = seq.params
    if seq.finished:
        return "sequence already finished"
    if seq.in_prefill:
        return "still prefilling (nothing to move; a retry re-prefills)"
    if not seq.output_ids:
        return "no tokens emitted yet"
    if params.max_tokens - len(seq.output_ids) < 1:
        return "about to finish (no remaining token budget)"
    if seq.lora_slot:
        return "LoRA sequences are not migratable (adapter-salted KV)"
    if params.logprobs is not None:
        return "logprobs streams are not migratable"
    if params.logit_bias:
        return "logit_bias streams are not migratable"
    if params.presence_penalty != 0.0 or params.frequency_penalty != 0.0:
        # these penalize GENERATED tokens only; the target sees the emitted
        # output as prompt, so the penalty state cannot be reconstructed
        # (repetition_penalty spans prompt+output and migrates fine)
        return "presence/frequency penalties are not migratable"
    return None
