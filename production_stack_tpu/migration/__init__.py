"""Live sequence migration + saturation-driven fleet control (ISSUE 10,
docs/migration.md).

Two halves:

- ``state``/``manager`` — the engine side of live sequence migration: a
  *running* sequence's KV page chain (shipped through the existing offload
  tiers, CRC-verified exactly like warm-start blobs), its sampling/decode
  state (token history = position + emitted count, params, seed), and its
  presentation metadata are snapshotted into a sealed wire document, shipped
  to a cooler engine, and resumed mid-stream with bit-identical greedy
  continuation. The router splices the continued SSE stream
  (router/request_service.py) so the client sees one uninterrupted response.
- ``controller`` — the saturation-driven fleet controller: a
  prometheus-adapter-style loop over the stack's own telemetry
  (``vllm_router:fleet_saturation``, per-backend saturation/queue depth)
  deciding **rebalance** (migrate the hottest long streams off the most
  saturated engine), **drain** (evacuate every sequence before SIGTERM —
  zero-loss scale-down), and **warm-up** (directory-driven prefetch into a
  scaled-up engine). ``scripts/fleet_controller.py`` is the CLI.
"""

from production_stack_tpu.migration.controller import (  # noqa: F401
    Action,
    BackendView,
    ControllerPolicy,
    FleetController,
    FleetDecider,
)
from production_stack_tpu.migration.manager import (  # noqa: F401
    MigrationError,
    MigrationManager,
)
from production_stack_tpu.migration.state import (  # noqa: F401
    SNAPSHOT_FORMAT,
    SequenceSnapshot,
    continuation_params,
    snapshot_from_wire,
    snapshot_to_wire,
    unmigratable_reason,
)
