"""Saturation-driven fleet controller (ISSUE 10 tentpole b).

The stack's first closed control loop over its own telemetry: the reference
stack scales replicas with prometheus-adapter but must kill or
drain-to-completion any pod it removes; with live sequence migration
(migration/manager.py) the controller instead *moves* work, so scale-down,
drain, and hot-spot rebalancing are zero-loss.

Structure mirrors the stack's other control surfaces:

- :class:`FleetDecider` — the PURE decision core (no I/O): given per-backend
  views and the fleet saturation signal it returns actions, applying
  **hysteresis** (rebalancing engages above the high watermark and stays
  engaged until pressure falls below the low watermark — no flapping at the
  threshold), a **cooldown** between actions, and a **cap on concurrent
  migrations** (each migration costs the source a device fetch and the
  target a restore; an unbounded storm would be self-inflicted overload).
  Unit-tested in isolation (tests/test_migration.py).
- :class:`FleetController` — the asyncio loop around it: scrapes each
  engine's ``/metrics`` (the same ``vllm:`` names the router scrapes, so it
  works against real and fake engines alike) and optionally the router's
  ``vllm_router:fleet_saturation`` gauge, executes decisions by POSTing
  ``/migrate_out`` to sources, and exposes its own Prometheus surface.
  ``scripts/fleet_controller.py`` is the CLI entrypoint; chaos
  ``--scenario scale-cycle`` drives it as a library.

Decisions by kind:

- ``rebalance`` — migrate the K hottest (longest-output) migratable streams
  from the most pressured engine to the least pressured one.
- ``drain`` — evacuate EVERY migratable sequence from a victim before it is
  SIGTERM'd (zero-loss scale-down); exposed as :meth:`FleetController.evacuate`.
- ``warm_up`` — a newly appeared engine is noted (it prefetches the fleet's
  top warm prefixes itself via ``--warm-prefetch-on-boot`` before /ready;
  the decision records that scale-up completed so operators can alert on a
  scale-up that never warmed).
- ``latency_protect`` — an engine whose *interactive* TTFT/ITL p99 (the
  ``vllm:interactive_{ttft,itl}_p99_ms`` gauges) breached its watermark
  sheds BATCH-class streams to the coolest peer before any interactive
  stream is touched (docs/failure-handling.md priority classes). Per-URL
  hysteresis: engages on breach, releases only when the p99 falls below
  ``latency_release_ratio`` x watermark.
"""

from __future__ import annotations

import asyncio
import re
import time
from dataclasses import dataclass, field
from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

_METRIC_LINE = re.compile(
    r"^(vllm:[a-z0-9_]+|vllm_router:[a-z0-9_]+)(?:\{[^}]*\})? ([0-9.eE+-]+)$"
)


@dataclass
class BackendView:
    """One engine's scraped state for a controller tick."""

    url: str
    healthy: bool = True
    saturated: bool = False
    waiting: int = 0
    running: int = 0
    # probed KV-fabric bandwidth summed over the engine's peer links
    # (vllm:kv_fabric_peer_bandwidth_bytes_per_sec; 0 = fabric off). A
    # migration target with a live fabric link receives the page chain
    # device-to-device instead of through the shared tier, so equal-pressure
    # target picks prefer the higher-bandwidth backend (docs/kv-fabric.md)
    fabric_bandwidth: float = 0.0
    # rolling interactive-class latency p99s the engine exports
    # (vllm:interactive_ttft_p99_ms / vllm:interactive_itl_p99_ms); 0 until
    # the first interactive request finishes — the latency_protect policy
    # treats 0 as "no signal", never as "fast"
    interactive_ttft_p99: float = 0.0
    interactive_itl_p99: float = 0.0
    # [{"request_id": ..., "output_tokens": ..., "priority": ...}, ...] —
    # migratable streams (priority defaults interactive when absent)
    migratable: list = field(default_factory=list)

    def batch_migratable(self) -> list:
        """Migratable streams in the batch SLO class — the only legal
        victims for latency_protect preemption."""
        return [
            r for r in self.migratable
            if r.get("priority", "interactive") == "batch"
        ]

    def rank_key(self, queue_ref: int) -> tuple:
        """Target-selection sort key: pressure first, probed fabric
        bandwidth as the tiebreak (higher bandwidth sorts earlier among
        equal-pressure backends — cheaper to ship a page chain to)."""
        return (self.pressure(queue_ref), -self.fabric_bandwidth)

    def pressure(self, queue_ref: int) -> float:
        """[0, 1] pressure score, mirroring the router's fleet-saturation
        per-backend term: saturation pins 1.0, else queue depth normalized
        by ``queue_ref`` with a small running-load term so two empty-queue
        backends still order by load."""
        if not self.healthy:
            return 0.0
        if self.saturated:
            return 1.0
        q = max(1, queue_ref)
        return min(1.0, self.waiting / q + 0.1 * min(1.0, self.running / q))


@dataclass
class Action:
    kind: str   # "rebalance" | "drain" | "warm_up" | "latency_protect"
    source: Optional[str] = None
    target: Optional[str] = None
    request_ids: list = field(default_factory=list)


@dataclass
class ControllerPolicy:
    """Policy knobs (docs/migration.md has the tuning table)."""

    # rebalance engages when (hottest - coolest) pressure exceeds this...
    rebalance_high_delta: float = 0.5
    # ...and stays engaged until the delta falls below this (hysteresis)
    rebalance_low_delta: float = 0.2
    # seconds between controller-initiated actions of the same kind
    cooldown_s: float = 10.0
    # migrations in flight fleet-wide; further rebalance decisions wait
    max_concurrent_migrations: int = 2
    # streams moved per rebalance decision (hottest/longest first)
    rebalance_k: int = 1
    # queue-depth normalizer for the pressure score (the router's
    # --saturation-queue-ref twin)
    saturation_queue_ref: int = 8
    # latency protection (0 disables): when an engine's interactive-class
    # TTFT or ITL p99 exceeds its watermark, batch streams migrate off it
    interactive_ttft_watermark_ms: float = 0.0
    interactive_itl_watermark_ms: float = 0.0
    # per-URL hysteresis release: disengage only when the breached p99
    # falls below watermark * this ratio (not at the watermark itself)
    latency_release_ratio: float = 0.7
    # batch streams moved per latency_protect decision
    latency_protect_k: int = 1


class FleetDecider:
    """Pure decision core — feed it views, read back actions. All state is
    explicit so the hysteresis/cooldown units test without a clock or I/O
    (``now`` is injected)."""

    def __init__(self, policy: ControllerPolicy):
        self.policy = policy
        self._engaged = False            # rebalance hysteresis latch
        self._latency_engaged: set = set()  # per-URL latency_protect latch
        self._last_action: dict = {}     # kind -> monotonic ts
        self._known_urls: set = set()    # for warm_up (new engine) detection
        self.decisions_total: dict = {
            "rebalance": 0, "drain": 0, "warm_up": 0, "latency_protect": 0,
        }

    def _cooled(self, kind: str, now: float) -> bool:
        last = self._last_action.get(kind)
        return last is None or now - last >= self.policy.cooldown_s

    def _note(self, kind: str, now: float) -> None:
        self._last_action[kind] = now
        self.decisions_total[kind] += 1

    def decide(
        self,
        views: list,
        inflight_migrations: int = 0,
        now: Optional[float] = None,
    ) -> list:
        """One tick. ``views`` are BackendView; returns Actions."""
        now = time.monotonic() if now is None else now
        p = self.policy
        actions: list = []
        healthy = [v for v in views if v.healthy]
        # warm_up: an engine url seen for the first time (scale-up landed)
        for v in healthy:
            if v.url not in self._known_urls and self._known_urls:
                actions.append(Action("warm_up", target=v.url))
                self._note("warm_up", now)
        self._known_urls.update(v.url for v in healthy)
        if len(healthy) < 2:
            self._engaged = False
            return actions
        scored = sorted(
            healthy, key=lambda v: v.rank_key(p.saturation_queue_ref)
        )
        cold, hot = scored[0], scored[-1]
        # latency protection (docs/failure-handling.md priority classes):
        # an engine failing its INTERACTIVE latency watermark sheds batch
        # streams to the coolest peer — batch is always preempted before
        # any interactive stream is considered, and an engine with no
        # interactive signal yet (p99 == 0) never engages
        for v in healthy:
            breach = (
                p.interactive_ttft_watermark_ms > 0
                and v.interactive_ttft_p99 > p.interactive_ttft_watermark_ms
            ) or (
                p.interactive_itl_watermark_ms > 0
                and v.interactive_itl_p99 > p.interactive_itl_watermark_ms
            )
            released = (
                p.interactive_ttft_watermark_ms <= 0
                or v.interactive_ttft_p99
                < p.interactive_ttft_watermark_ms * p.latency_release_ratio
            ) and (
                p.interactive_itl_watermark_ms <= 0
                or v.interactive_itl_p99
                < p.interactive_itl_watermark_ms * p.latency_release_ratio
            )
            if v.url not in self._latency_engaged and breach:
                self._latency_engaged.add(v.url)
            elif v.url in self._latency_engaged and released:
                self._latency_engaged.discard(v.url)
            if not (
                v.url in self._latency_engaged
                and inflight_migrations < p.max_concurrent_migrations
                and self._cooled("latency_protect", now)
            ):
                continue
            budget = min(
                p.latency_protect_k,
                p.max_concurrent_migrations - inflight_migrations,
            )
            victims = sorted(
                v.batch_migratable(),
                key=lambda r: -int(r.get("output_tokens", 0)),
            )[:budget]
            targets = [t for t in scored if t.url != v.url]
            if victims and targets:
                actions.append(Action(
                    "latency_protect", source=v.url, target=targets[0].url,
                    request_ids=[r["request_id"] for r in victims],
                ))
                self._note("latency_protect", now)
        delta = hot.pressure(p.saturation_queue_ref) - cold.pressure(
            p.saturation_queue_ref
        )
        # hysteresis: engage above the high watermark, stay engaged until
        # the delta falls below the low one — a delta hovering at the
        # threshold must not flap the controller on and off every tick
        if not self._engaged and delta >= p.rebalance_high_delta:
            self._engaged = True
        elif self._engaged and delta < p.rebalance_low_delta:
            self._engaged = False
        if (
            self._engaged
            and hot.migratable
            and inflight_migrations < p.max_concurrent_migrations
            and self._cooled("rebalance", now)
        ):
            budget = min(
                p.rebalance_k,
                p.max_concurrent_migrations - inflight_migrations,
            )
            victims = sorted(
                hot.migratable,
                key=lambda r: -int(r.get("output_tokens", 0)),
            )[:budget]
            if victims:
                actions.append(Action(
                    "rebalance", source=hot.url, target=cold.url,
                    request_ids=[r["request_id"] for r in victims],
                ))
                self._note("rebalance", now)
        return actions

    def plan_drain(self, views: list, victim_url: str) -> list:
        """Evacuation plan: every migratable stream on the victim, spread
        over the surviving backends coolest-first (round-robin so one target
        does not absorb the whole working set)."""
        victim = next((v for v in views if v.url == victim_url), None)
        survivors = sorted(
            (v for v in views if v.url != victim_url and v.healthy),
            key=lambda v: v.rank_key(self.policy.saturation_queue_ref),
        )
        if victim is None or not survivors or not victim.migratable:
            return []
        actions = []
        for i, r in enumerate(sorted(
            victim.migratable, key=lambda r: -int(r.get("output_tokens", 0))
        )):
            actions.append(Action(
                "drain", source=victim_url,
                target=survivors[i % len(survivors)].url,
                request_ids=[r["request_id"]],
            ))
        if actions:
            self._note("drain", time.monotonic())
        return actions


class FleetController:
    """Asyncio loop: scrape -> decide -> execute. HTTP only (aiohttp); the
    controller is a pure client of the engines' and router's surfaces, so it
    runs anywhere — a sidecar, a CLI, or in-process in the chaos harness."""

    def __init__(
        self,
        engine_urls: list,
        router_url: Optional[str] = None,
        policy: Optional[ControllerPolicy] = None,
        tick_interval_s: float = 5.0,
        migrate_timeout_s: float = 30.0,
    ):
        self.engine_urls = list(engine_urls)
        self.router_url = router_url
        self.policy = policy or ControllerPolicy()
        self.decider = FleetDecider(self.policy)
        self.tick_interval_s = tick_interval_s
        self.migrate_timeout_s = migrate_timeout_s
        self._session = None
        # request_id -> started monotonic; entries retire on completion or
        # timeout so a wedged migration cannot pin the concurrency cap
        self._inflight: dict = {}
        self.migrations_started = 0
        self.migrations_failed = 0
        self.last_fleet_saturation = 0.0

    async def _client(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # -- scraping ------------------------------------------------------------

    @staticmethod
    def parse_metrics(text: str) -> dict:
        """Summed values per metric name (label sets collapse, like the
        router's EngineStats parser)."""
        out: dict = {}
        for line in text.splitlines():
            line = line.strip()
            m = _METRIC_LINE.match(line)
            if m:
                # label-collapsed sum; names used here are single-series
                out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
        return out

    async def _fetch_text(self, url: str) -> Optional[str]:
        try:
            session = await self._client()
            async with session.get(url) as resp:
                if resp.status != 200:
                    return None
                return await resp.text()
        except Exception:  # noqa: BLE001 - a dead backend is a view, not a crash
            return None

    async def _fetch_json(self, url: str) -> Optional[dict]:
        try:
            session = await self._client()
            async with session.get(url) as resp:
                if resp.status != 200:
                    return None
                return await resp.json()
        except Exception:  # noqa: BLE001
            return None

    async def view_of(self, url: str) -> BackendView:
        text = await self._fetch_text(f"{url}/metrics")
        if text is None:
            return BackendView(url=url, healthy=False)
        vals = self.parse_metrics(text)
        view = BackendView(
            url=url,
            healthy=True,
            saturated=bool(vals.get("vllm:engine_saturated", 0)),
            waiting=int(vals.get("vllm:num_requests_waiting", 0)),
            running=int(vals.get("vllm:num_requests_running", 0)),
            fabric_bandwidth=float(
                vals.get("vllm:kv_fabric_peer_bandwidth_bytes_per_sec", 0.0)
            ),
            interactive_ttft_p99=float(
                vals.get("vllm:interactive_ttft_p99_ms", 0.0)
            ),
            interactive_itl_p99=float(
                vals.get("vllm:interactive_itl_p99_ms", 0.0)
            ),
        )
        listing = await self._fetch_json(f"{url}/migratable")
        if listing:
            view.migratable = [
                r for r in listing.get("requests", [])
                if r.get("migratable", True)
            ]
        return view

    async def gather_views(self) -> list:
        return list(await asyncio.gather(
            *(self.view_of(u) for u in self.engine_urls)
        ))

    async def fleet_saturation(self) -> float:
        """The router's autoscaling gauge when a router is configured, else
        the mean of the per-backend pressure scores."""
        if self.router_url:
            text = await self._fetch_text(f"{self.router_url}/metrics")
            if text is not None:
                vals = self.parse_metrics(text)
                if "vllm_router:fleet_saturation" in vals:
                    return float(vals["vllm_router:fleet_saturation"])
        views = await self.gather_views()
        if not views:
            return 0.0
        return sum(
            v.pressure(self.policy.saturation_queue_ref) for v in views
        ) / len(views)

    # -- execution -----------------------------------------------------------

    def _sweep_inflight(self) -> None:
        cutoff = time.monotonic() - self.migrate_timeout_s
        for rid in [r for r, t in self._inflight.items() if t < cutoff]:
            del self._inflight[rid]

    async def migrate(self, source: str, request_id: str, target: str) -> bool:
        """POST /migrate_out on the source; True when the stream moved."""
        self._inflight[request_id] = time.monotonic()
        self.migrations_started += 1
        try:
            session = await self._client()
            async with session.post(
                f"{source}/migrate_out",
                json={"request_id": request_id, "target_url": target},
            ) as resp:
                body = await resp.json()
                ok = resp.status == 200 and bool(body.get("migrated"))
        except Exception as e:  # noqa: BLE001 - failure = pick another victim
            logger.warning(
                "migrate_out %s %s -> %s failed: %s",
                request_id, source, target, e,
            )
            ok = False
        finally:
            self._inflight.pop(request_id, None)
        if not ok:
            self.migrations_failed += 1
        return ok

    async def execute(self, action: Action) -> int:
        """Run one action; returns migrations that succeeded."""
        if action.kind == "warm_up":
            logger.info(
                "fleet controller: engine %s scaled up (boot prefetch is "
                "engine-side: --warm-prefetch-on-boot)", action.target,
            )
            return 0
        n = 0
        for rid in action.request_ids:
            if await self.migrate(action.source, rid, action.target):
                n += 1
        return n

    async def tick(self) -> list:
        """One control iteration: scrape, decide, execute. Returns the
        actions taken (chaos/tests introspect them)."""
        self._sweep_inflight()
        views = await self.gather_views()
        self.last_fleet_saturation = await self.fleet_saturation()
        actions = self.decider.decide(views, len(self._inflight))
        for a in actions:
            await self.execute(a)
        return actions

    async def run(self, stop: Optional[asyncio.Event] = None) -> None:
        stop = stop or asyncio.Event()
        while not stop.is_set():
            try:
                await self.tick()
            except Exception:  # noqa: BLE001 - the loop must outlive one bad tick
                logger.exception("fleet controller tick failed")
            try:
                await asyncio.wait_for(stop.wait(), self.tick_interval_s)
            except asyncio.TimeoutError:
                pass

    async def evacuate(
        self, victim_url: str, deadline_s: float = 60.0
    ) -> dict:
        """Zero-loss drain: migrate EVERY migratable sequence off the victim
        before the operator SIGTERMs it. Loops (new streams may land on the
        victim while it evacuates — callers should pull it from routing
        first) until the victim reports no running work or the deadline
        passes. Returns a report dict the chaos scenario asserts on."""
        t0 = time.monotonic()
        moved = failed = rounds = 0
        while time.monotonic() - t0 < deadline_s:
            rounds += 1
            views = await self.gather_views()
            victim = next((v for v in views if v.url == victim_url), None)
            if victim is None or not victim.healthy:
                break
            if not victim.migratable and victim.running == 0:
                break
            plan = self.decider.plan_drain(views, victim_url)
            if not plan:
                # running work that is not (yet) migratable: give it a beat
                # to emit its first token or finish
                await asyncio.sleep(0.2)
                continue
            for a in plan:
                n = await self.execute(a)
                moved += n
                failed += len(a.request_ids) - n
            await asyncio.sleep(0.1)
        views = await self.gather_views()
        victim = next((v for v in views if v.url == victim_url), None)
        return {
            "victim": victim_url,
            "moved": moved,
            "failed": failed,
            "rounds": rounds,
            "evacuation_s": round(time.monotonic() - t0, 3),
            "residual_running": victim.running if victim else 0,
            "residual_migratable": len(victim.migratable) if victim else 0,
        }

    # -- observability -------------------------------------------------------

    def metrics_text(self) -> str:
        """Prometheus exposition for --metrics-port:
        vllm:fleet_controller_decisions_total{kind=...},
        vllm:fleet_controller_migrations_started_total,
        vllm:fleet_controller_migrations_failed_total,
        vllm:fleet_controller_migrations_inflight,
        vllm:fleet_controller_fleet_saturation."""
        lines = ["# TYPE vllm:fleet_controller_decisions_total counter"]
        for kind, n in sorted(self.decider.decisions_total.items()):
            lines.append(
                "vllm:fleet_controller_decisions_total"
                f'{{kind="{kind}"}} {n}'
            )
        lines += [
            "# TYPE vllm:fleet_controller_migrations_started_total counter",
            f"vllm:fleet_controller_migrations_started_total "
            f"{self.migrations_started}",
            "# TYPE vllm:fleet_controller_migrations_failed_total counter",
            f"vllm:fleet_controller_migrations_failed_total "
            f"{self.migrations_failed}",
            "# TYPE vllm:fleet_controller_migrations_inflight gauge",
            f"vllm:fleet_controller_migrations_inflight {len(self._inflight)}",
            "# TYPE vllm:fleet_controller_fleet_saturation gauge",
            f"vllm:fleet_controller_fleet_saturation "
            f"{round(self.last_fleet_saturation, 4)}",
        ]
        return "\n".join(lines) + "\n"


__all__ = [
    "Action",
    "BackendView",
    "ControllerPolicy",
    "FleetController",
    "FleetDecider",
]
