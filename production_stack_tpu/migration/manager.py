"""Engine-side migration choreography: freeze -> ship -> commit | rollback.

State machine (source engine, one sequence):

    RUNNING --freeze--> FROZEN --commit--> MIGRATED (finish_reason
       ^                   |                "migrated"; stream ends with the
       |                   |                control event the router splices on)
       +----- rollback ----+  (target refused / unreachable: the sequence
                               re-enters the running set and decoding resumes
                               locally — nothing was client-visible)

Target engine: /migrate_in parks a continuation (api_server), which the
router attaches to with /migrate_attach. Everything here that touches
scheduler or device state runs ON the engine device thread via
``engine._run_on_device_thread`` — the same serialization discipline as
LoRA updates and sleep/wake — so no extra locking against the step loop is
needed; ``_frozen`` is device-thread-owned by construction.

KV movement rides the existing offload path: full pages are saved
content-addressed (confirmed-save contract, connector.save_pages), persisted
past DRAM for cpu+disk hierarchies (the warm-start lesson — puts land in
DRAM and disk only sees evictions), CRC-verified by every reader, and
advertised to the fleet KV directory when one is configured.
"""

from __future__ import annotations

import time

from production_stack_tpu.migration.state import (
    SequenceSnapshot,
    params_to_doc,
    unmigratable_reason,
)
from production_stack_tpu.utils.logging import init_logger
from production_stack_tpu.utils.metrics import LATENCY_BUCKETS, Histogram

logger = init_logger(__name__)


class MigrationError(RuntimeError):
    """A sequence cannot be (or is no longer) migratable; the caller maps
    this to a 409 and the controller picks another victim."""


class MigrationManager:
    """Owned by LLMEngine (``engine.migration``); api_server drives it from
    executor threads so the event loop never blocks on a device command."""

    def __init__(self, engine):
        self.engine = engine
        # counters are single-writer enough for unlocked ints: out/pages/
        # failures mutate on the device thread (freeze/commit) or the event
        # loop (ship failures), and stats() readers tolerate a torn read the
        # same way every other engine counter does
        self.migrations_out = 0
        self.migrations_in = 0
        self.pages_moved = 0
        self.fabric_pages = 0
        self.failures = 0
        # freeze -> commit wall time on the source (the stream-stall window
        # a client could observe between the last source chunk and the
        # router's attach)
        self.duration_hist = Histogram(
            "vllm:migration_duration_seconds", LATENCY_BUCKETS,
            "Source-side migration duration (freeze to commit)",
        )
        # seq_id -> monotonic freeze time
        self._freeze_started: dict[str, float] = {}  # owned-by: device-thread

    # -- source side ---------------------------------------------------------

    def freeze_and_snapshot(
        self, seq_id: str, meta: dict, fabric_addr=None
    ) -> SequenceSnapshot:
        """Freeze a running sequence (it stops decoding but keeps its pages)
        and build its snapshot: full-page KV shipped to the target over the
        KV fabric when ``fabric_addr`` names its listener (device-to-device
        handoff, zero shared-tier I/O), else saved through the offload tiers
        (confirmed prefix only); plus token history, params, presentation
        meta. Runs on the device thread; raises MigrationError when the
        sequence is gone or semantically unmigratable."""
        return self.engine._run_on_device_thread(
            lambda: self._freeze(seq_id, meta, fabric_addr),
            what=f"migrate freeze {seq_id}",
        )

    def _freeze(
        self, seq_id: str, meta: dict, fabric_addr=None
    ) -> SequenceSnapshot:
        engine = self.engine
        sched = engine.scheduler
        seq = next(
            (s for s in sched.running if s.seq_id == seq_id and not s.finished),
            None,
        )
        if seq is None:
            raise MigrationError(f"sequence {seq_id!r} is not running")
        reason = unmigratable_reason(seq)
        if reason is not None:
            raise MigrationError(reason)
        from production_stack_tpu.engine.kv_manager import prefix_hashes

        tokens = seq.prompt_ids + seq.output_ids
        # only FULLY-WRITTEN pages ship: the newest emitted token's KV is not
        # written until it is fed back as the next step's input, so the page
        # holding position len(tokens)-1 is incomplete and must recompute
        n_full = (len(tokens) - 1) // engine.kv.page_size
        hashes = prefix_hashes(tokens, engine.kv.page_size, seq.cache_salt)[:n_full]
        confirmed = 0
        offload = engine._offload
        if (
            fabric_addr
            and hashes
            and getattr(engine, "_fabric_client", None) is not None
        ):
            # fabric handoff (docs/kv-fabric.md): the page chain moves
            # engine-to-engine as (pages, scales) frames and lands straight
            # in the TARGET's local tiers — the shared tier never sees the
            # bytes. The tier save below remains the fallback when the
            # fabric could not cover the chain (counted on
            # kv_fabric_fallbacks_total by the client).
            pairs = [(p, h.hex()) for p, h in zip(seq.pages, hashes)]
            shipped = set(engine.fabric_ship_pairs(fabric_addr, pairs))
            while confirmed < len(hashes) and hashes[confirmed].hex() in shipped:
                confirmed += 1
            self.fabric_pages += confirmed
        if confirmed == 0 and offload is not None and hashes:
            pairs = list(zip(seq.pages, hashes))
            saved = offload.save_pages(pairs)
            # the restorable chain must be CONTIGUOUS from the head — the
            # target's prefix match truncates at the first miss anyway
            while confirmed < len(hashes) and hashes[confirmed] in saved:
                confirmed += 1
            store = offload.store
            if store.cpu is not None and store.disk is not None:
                # cpu+disk hierarchy: puts land in DRAM and disk only sees
                # DRAM evictions — force durable copies so a target sharing
                # the disk tier (or a source crash before the pull) still
                # restores (same contract as warm-start manifests)
                for h in hashes[:confirmed]:
                    store.persist(h.hex())
            if engine.kv.directory is not None and confirmed:
                # truthful fleet hint: these blobs are confirmed in the
                # shared tier (when one exists; publish_shared gates itself)
                engine.kv.directory.publish_shared([
                    (h, i, 1.0) for i, h in enumerate(hashes[:confirmed])
                ])
        # freeze: out of the running set, pages kept, no more decode steps
        sched.running.remove(seq)
        engine._frozen[seq_id] = seq
        self._freeze_started[seq_id] = time.monotonic()
        logger.info(
            "migration: froze %s (%d tokens, %d/%d pages restorable)",
            seq_id, len(tokens), confirmed, n_full,
        )
        return SequenceSnapshot(
            request_id=meta.get("request_id", seq_id),
            model=engine.cfg.name,
            page_size=engine.kv.page_size,
            tokens=list(tokens),
            prompt_len=len(seq.prompt_ids),
            output_len=len(seq.output_ids),
            params=params_to_doc(seq.params),
            page_hashes=[h.hex() for h in hashes[:confirmed]],
            meta=dict(meta),
        )

    def commit(self, seq_id: str, pages_moved: int) -> None:
        """The target accepted: finish the frozen sequence with reason
        "migrated" (registers its pages in the local prefix cache and frees
        them) and emit the terminal output the API layer converts into the
        stream-handoff control event. Device thread."""

        def run():
            seq = self.engine._frozen.pop(seq_id, None)
            if seq is None or seq.finished:
                return
            self.engine.scheduler._finish(seq, "migrated")
            self.engine._emit(seq, "")
            self.migrations_out += 1
            self.pages_moved += pages_moved
            t0 = self._freeze_started.pop(seq_id, None)
            if t0 is not None:
                self.duration_hist.observe(time.monotonic() - t0)

        self.engine._run_on_device_thread(run, what=f"migrate commit {seq_id}")

    def rollback(self, seq_id: str) -> None:
        """The target refused or the ship failed: the sequence re-enters the
        running set and decoding resumes locally — the client stream never
        noticed. Device thread."""

        def run():
            seq = self.engine._frozen.pop(seq_id, None)
            self._freeze_started.pop(seq_id, None)
            self.failures += 1
            if seq is not None and not seq.finished:
                self.engine.scheduler.running.append(seq)
                logger.warning(
                    "migration: rolled back %s (resuming locally)", seq_id
                )

        self.engine._run_on_device_thread(run, what=f"migrate rollback {seq_id}")

    # -- target side ---------------------------------------------------------

    def prefetch_pages(self, hashes_hex: list) -> int:
        """Pull the snapshot's blobs into the LOCAL host tiers (executor
        thread, before the continuation is admitted) so the device-thread
        restore at admission reads locally. ``store.get`` walks
        local -> remote, CRC-verifies, and promotes; a miss or corruption
        truncates the chain there — the tail recomputes, which is always
        correct (the warm-restart contract)."""
        offload = self.engine._offload
        if offload is None or not hashes_hex:
            return 0
        from production_stack_tpu.kvoffload.serde import (
            KVIntegrityError,
            verify_blob,
        )

        store = offload.store
        n = 0
        for key in hashes_hex:
            try:
                if store.contains_local(key) or store.get(key) is not None:
                    n += 1
                    continue
                # co-located engines sharing a disk directory: the source
                # wrote the blob AFTER this process built its disk index, so
                # the indexed get-walk misses it — read the FILE directly
                # (the warm-start get_fresh path), verify, and index it
                blob = (
                    store.disk.get_fresh(key)
                    if store.disk is not None else None
                )
                if blob is None:
                    break  # chain broken: later chunks cannot extend anyway
                verify_blob(blob)
                store.put_local(key, blob)
                n += 1
            except KVIntegrityError:
                logger.warning("migration prefetch: corrupt blob %s", key)
                break
            except Exception:  # noqa: BLE001 - recompute covers any tier error
                logger.exception("migration prefetch failed for %s", key)
                break
        return n

    def note_migrate_in(self) -> None:
        self.migrations_in += 1

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """Rendered by api_server /metrics under the vllm: namespace:
        vllm:migrations_out_total, vllm:migrations_in_total,
        vllm:migration_pages_moved_total, vllm:migration_failures_total
        (plus the vllm:migration_duration_seconds histogram)."""
        return {
            "migrations_out_total": self.migrations_out,
            "migrations_in_total": self.migrations_in,
            "migration_pages_moved_total": self.pages_moved,
            "migration_fabric_pages_total": self.fabric_pages,
            "migration_failures_total": self.failures,
        }
