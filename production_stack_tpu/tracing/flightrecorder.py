"""Engine flight recorder: a bounded, lock-free ring of structured engine
events for postmortem and live introspection.

The tracing subsystem (collector.py) answers "where did THIS request's time
go"; the flight recorder answers the complementary question — "what was the
ENGINE doing when things went wrong": which scheduler dispatches, KV
evictions/spills/restores, admission sheds, and JAX compiles surrounded a bad
tail or a chaos event. Events are cheap dicts stamped with a monotonically
increasing sequence number, the engine step index, wall-clock time, and the
active trace id (when the triggering request carries a sampled PR-1 span
context), so a flight-recorder window cross-links to ``/v1/traces`` spans by
trace id and to logs by request id.

Recording uses the same lock-free pattern as the span collector: an
``itertools.count`` cursor hands each writer a distinct ring slot (``next()``
is atomic under the GIL), so the device thread pays a dict build + one list
store per event and nothing blocks. Memory is bounded by ``capacity``.

Surfaces:

- ``GET /v1/debug/flightrecorder`` (engine + fake engine, debug-gated on the
  real engine): JSON export, filterable by ``?request_id=`` / ``?trace_id=`` /
  ``?kind=`` / ``?since_step=`` / ``?until_step=`` / ``?limit=``.
- **Anomaly dumps**: ``dump(reason)`` writes the current window to
  ``<dump_dir>/flightrecorder-<reason>-<ts>.json`` for postmortems. Triggers
  wired by the engine/fake engine: engine-loop step failure, SIGTERM drain,
  shed bursts, and the TTFT p99-breach watermark. Rate-limited per reason so
  a sustained breach cannot fill the disk (crash/drain dumps bypass the
  limit — there is no second chance to take them).

Event kinds recorded by the engine (docs/observability.md):

- ``sched``  — one per dispatched batch: kind, rows, bursts, chunk tokens,
  interleave-gate inputs/decision, queue depths, seq + trace ids.
- ``step``   — device wall time of a fetched dispatch.
- ``kv``     — page-manager ops (evict/spill/restore/warm_restore) with page
  counts and victim reuse scores.
- ``shed``   — admission-control sheds (queue_full / queue_deadline / api).
- ``compile``— JAX backend compiles (duration via jax.monitoring) and new
  jit program variants at the runner's cache boundaries.
- ``slo``    — per-request terminal records (mirrors /slo_records).
- ``anomaly``— a dump was taken (reason + path), recorded into the ring
  itself so later exports show the trigger history.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

DEFAULT_CAPACITY = 8192

# minimum seconds between two disk dumps for the SAME reason (forced dumps —
# crash / SIGTERM — bypass this)
DUMP_MIN_INTERVAL_S = 10.0


class FlightRecorder:
    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        enabled: bool = True,
        dump_dir: Optional[str] = None,
    ):
        self.capacity = max(16, int(capacity))
        self.enabled = bool(enabled)
        self.dump_dir = dump_dir
        self._slots: list = [None] * self.capacity  # owned-by: any
        self._cursor = itertools.count()  # owned-by: any
        self._last_dump: dict[str, float] = {}  # guarded-by: _dump_lock
        self._dump_lock = threading.Lock()
        self.dumps_total = 0

    # -- recording ----------------------------------------------------------

    @property
    def recorded(self) -> int:
        """Events recorded since construction/reset (atomic cursor peek)."""
        return self._cursor.__reduce__()[1][0]

    @property
    def dropped(self) -> int:
        """Events overwritten by the ring wrapping (bounded-memory cost)."""
        return max(0, self.recorded - self.capacity)

    def record(
        self,
        kind: str,
        *,
        step: int = -1,
        trace_id: Optional[str] = None,
        **data,
    ) -> None:
        """Store one event. The entire hot-path cost when disabled is one
        attribute check; when enabled, a dict build + one atomic slot claim
        (same lock-free scheme as the span collector's ring)."""
        if not self.enabled:
            return
        seq = next(self._cursor)
        self._slots[seq % self.capacity] = {
            "seq": seq,
            "kind": kind,
            "t": time.time(),
            "step": step,
            "trace_id": trace_id,
            "data": data,
        }

    # -- reading ------------------------------------------------------------

    def events(
        self,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        kind: Optional[str] = None,
        since_step: Optional[int] = None,
        until_step: Optional[int] = None,
        limit: int = 0,
    ) -> list[dict]:
        """Filtered, chronologically ordered (by seq) event snapshot.

        ``request_id`` matches the event's ``seq_id``/``request_id`` fields or
        membership in its ``seq_ids`` list (batch events carry the first few
        member ids). Events recorded outside any engine step (KV-manager ops,
        compile listener — ``step`` -1) are always inside a step-range
        window: a postmortem cut by step range must not silently claim "no
        evictions, no compiles". A reader may race a writer mid-overwrite
        and see either the old or the new event in a slot — both are whole
        events, so snapshots never tear."""
        out = []
        for ev in list(self._slots):
            if ev is None:
                continue
            if kind is not None and ev["kind"] != kind:
                continue
            if trace_id is not None and ev.get("trace_id") != trace_id:
                continue
            if since_step is not None and 0 <= ev["step"] < since_step:
                continue
            if (
                until_step is not None
                and ev["step"] >= 0
                and ev["step"] > until_step
            ):
                continue
            if request_id is not None:
                d = ev["data"]
                if not (
                    d.get("seq_id") == request_id
                    or d.get("request_id") == request_id
                    or request_id in (d.get("seq_ids") or ())
                ):
                    continue
            out.append(ev)
        out.sort(key=lambda e: e["seq"])
        if limit and limit > 0:
            out = out[-limit:]
        return out

    def export(self, **filters) -> dict:
        """JSON-serializable payload for /v1/debug/flightrecorder and the
        anomaly dump files (scripts/trace_report.py --flightrecorder consumes
        exactly this shape)."""
        return {
            "capacity": self.capacity,
            "enabled": self.enabled,
            "recorded_total": self.recorded,
            "dropped_total": self.dropped,
            "dumps_total": self.dumps_total,
            "exported_at": time.time(),
            "events": self.events(**filters),
        }

    # -- anomaly dumps ------------------------------------------------------

    def dump(self, reason: str, force: bool = False) -> Optional[str]:
        """Write the current window to disk for postmortem use. Returns the
        file path, or None when no dump dir is configured / the per-reason
        rate limit holds. ``force`` bypasses the limit (crash/SIGTERM —
        the process is about to die, this is the only chance)."""
        if not self.dump_dir:
            return None
        with self._dump_lock:
            now = time.monotonic()
            last = self._last_dump.get(reason, -1e18)
            if not force and now - last < DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump[reason] = now
        # the trigger itself becomes part of the record BEFORE export, so the
        # dump (and later live exports) show it in sequence
        self.record("anomaly", reason=reason)
        path = os.path.join(
            self.dump_dir,
            f"flightrecorder-{reason}-{int(time.time() * 1000)}.json",
        )
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            payload = self.export()
            payload["reason"] = reason
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)  # readers only ever see whole dumps
        except OSError:
            return None
        self.dumps_total += 1
        return path

    def dump_async(self, reason: str) -> None:
        """Rate-limit-aware background dump for hot-path triggers (shed
        bursts on the event loop, TTFT breaches on the device thread):
        serializing an 8k-event ring inline would stall serving exactly when
        it is most loaded. The cheap pre-check races dump()'s authoritative
        one at worst into a spare no-op thread; forced dumps (crash/SIGTERM)
        stay synchronous — the process is about to die."""
        if not self.dump_dir:
            return
        # dump() re-reads _last_dump under _dump_lock authoritatively; the
        # worst a torn read here costs is one spare no-op thread
        last = self._last_dump.get(reason, -1e18)  # graftcheck: disable=GC004 — racy-by-design rate-limit pre-check, dump() re-checks under the lock
        if time.monotonic() - last < DUMP_MIN_INTERVAL_S:
            return
        threading.Thread(
            target=self.dump, args=(reason,), daemon=True
        ).start()

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def reset(self) -> None:
        """Debug/bench only: clear the ring so a phase's events describe
        that phase."""
        self._slots = [None] * self.capacity
        self._cursor = itertools.count()


# -- process-global recorder --------------------------------------------------

_recorder = FlightRecorder()
_lock = threading.Lock()


def configure_flightrecorder(
    capacity: Optional[int] = None,
    enabled: Optional[bool] = None,
    dump_dir: Optional[str] = None,
) -> FlightRecorder:
    """(Re)configure the process-global recorder. Resizing replaces the ring
    (old events drop); enable/dump-dir changes keep recorded events."""
    global _recorder
    with _lock:
        if capacity is not None and int(capacity) != _recorder.capacity:
            _recorder = FlightRecorder(
                capacity=capacity,
                enabled=_recorder.enabled if enabled is None else enabled,
                dump_dir=dump_dir if dump_dir is not None else _recorder.dump_dir,
            )
        else:
            if enabled is not None:
                _recorder.enabled = bool(enabled)
            if dump_dir is not None:
                _recorder.dump_dir = dump_dir
        return _recorder


def get_flightrecorder() -> FlightRecorder:
    return _recorder


def export_for_query(query) -> "tuple[dict, int]":
    """Shared ``GET /v1/debug/flightrecorder`` implementation for every server
    hosting the recorder (engine, fake engine): parse filters from an HTTP
    query mapping and return ``(json_payload, status)``."""
    filters: dict = {}
    for key in ("request_id", "trace_id", "kind"):
        if query.get(key):
            filters[key] = query[key]
    for key in ("since_step", "until_step", "limit"):
        raw = query.get(key)
        if raw is None:
            continue
        try:
            filters[key] = int(raw)
        except (TypeError, ValueError):
            return {"error": f"{key} must be an int"}, 400
    return get_flightrecorder().export(**filters), 200


def render_flightrecorder_metrics(labels: str) -> list[str]:
    """Prometheus exposition lines for the recorder's own health (the
    'recorder drops' dashboard panel): a wrapped ring silently loses the
    oldest events, and a postmortem built on a holey window must say so."""
    fr = get_flightrecorder()
    return [
        "# TYPE vllm:flightrecorder_events_total counter",
        f"vllm:flightrecorder_events_total{{{labels}}} {fr.recorded}",
        "# TYPE vllm:flightrecorder_dropped_events_total counter",
        f"vllm:flightrecorder_dropped_events_total{{{labels}}} {fr.dropped}",
        "# TYPE vllm:flightrecorder_capacity gauge",
        f"vllm:flightrecorder_capacity{{{labels}}} {fr.capacity}",
        "# TYPE vllm:flightrecorder_enabled gauge",
        f"vllm:flightrecorder_enabled{{{labels}}} {int(fr.enabled)}",
        "# TYPE vllm:flightrecorder_dumps_total counter",
        f"vllm:flightrecorder_dumps_total{{{labels}}} {fr.dumps_total}",
    ]
