"""Request-level distributed tracing across router -> engine -> KV-offload.

A W3C-``traceparent`` span context enters at the router proxy, propagates to
the engine API server over the proxied request's headers, and is recorded
against the serving hot phases — routing decision, engine queue wait, prefill,
decode, KV-offload spill/restore — in a bounded in-process ring buffer.
``/v1/traces`` on both servers exports the buffer as JSON;
``scripts/trace_report.py`` renders a per-phase latency table from an export;
the four per-phase Prometheus histograms (tracing/metrics.py) feed the
dashboard's phase-breakdown panels. See docs/tracing.md.
"""

from production_stack_tpu.tracing.collector import (
    Span,
    SpanCollector,
    configure_tracing,
    current_context,
    export_for_query,
    get_collector,
    render_collector_metrics,
    reset_current,
    set_current,
)
from production_stack_tpu.tracing.flightrecorder import (
    FlightRecorder,
    configure_flightrecorder,
    get_flightrecorder,
    render_flightrecorder_metrics,
)
from production_stack_tpu.tracing.context import (
    TRACEPARENT_HEADER,
    SpanContext,
    gen_span_id,
    gen_trace_id,
)
from production_stack_tpu.tracing.metrics import (
    decode_step_time_hist,
    interleaved_decode_hist,
    offload_restore_hist,
    prefill_chunk_hist,
    prefill_time_hist,
    queue_time_hist,
    render_phase_histograms,
    reset_phase_histograms,
)

__all__ = [
    "FlightRecorder",
    "Span",
    "SpanCollector",
    "SpanContext",
    "TRACEPARENT_HEADER",
    "configure_flightrecorder",
    "configure_tracing",
    "current_context",
    "decode_step_time_hist",
    "export_for_query",
    "gen_span_id",
    "gen_trace_id",
    "get_collector",
    "get_flightrecorder",
    "interleaved_decode_hist",
    "offload_restore_hist",
    "prefill_chunk_hist",
    "prefill_time_hist",
    "queue_time_hist",
    "render_collector_metrics",
    "render_flightrecorder_metrics",
    "render_phase_histograms",
    "reset_current",
    "reset_phase_histograms",
    "set_current",
]
