"""In-process span collector: bounded ring buffer + head-based sampling.

Spans are recorded from latency-critical paths (the router's streaming proxy
and the engine device thread), so the collector is deliberately minimal:

- **Ring buffer.** A fixed-size slot list plus an ``itertools.count`` cursor.
  ``next()`` on a count is atomic under the GIL, so concurrent writers each
  claim a distinct slot without a lock on the hot path; the oldest spans are
  overwritten when the buffer wraps. Memory is bounded by ``capacity``
  regardless of traffic.
- **Head-based sampling.** The root of a trace decides sampling once —
  deterministically from the trace id — and the decision rides the
  ``traceparent`` flags, so a trace is recorded end-to-end or not at all.
  ``sample_rate=0.0`` records nothing (record() is a flag check and return);
  ``1.0`` records everything.

The process-global collector is shared by every server hosted in the process
(router and engine both, when co-hosted as in bench.py), which is exactly
what lets ``/v1/traces`` on either endpoint stitch a full trace together.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from production_stack_tpu.tracing.context import SpanContext

DEFAULT_CAPACITY = 4096


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float          # epoch seconds
    duration: float       # seconds
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration_ms": round(self.duration * 1000, 3),
            "attrs": self.attrs,
        }


class SpanCollector:
    def __init__(
        self, capacity: int = DEFAULT_CAPACITY, sample_rate: float = 1.0
    ):
        self.capacity = max(1, int(capacity))
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self._slots: list = [None] * self.capacity
        self._cursor = itertools.count()
        # head-sampling rejections: record() calls that arrived with a real
        # but UNSAMPLED context. Counted (atomically, same count trick as the
        # slot cursor) so span loss is visible on /metrics BEFORE someone
        # debugs a latency tail with a trace that silently isn't there.
        self._rejected = itertools.count()

    @property
    def recorded(self) -> int:
        """Count of record() calls that stored a span since construction or
        the last reset(). Peeks the slot cursor — the same atomic counter
        that claims slots — so concurrent writers cannot lose updates the
        way a separate ``+= 1`` (a non-atomic read-modify-write) would."""
        # count.__reduce__() -> (count, (next_value,)) without consuming
        return self._cursor.__reduce__()[1][0]

    @property
    def overwritten(self) -> int:
        """Spans lost to the ring wrapping: every record past ``capacity``
        overwrote the oldest surviving span. The exact silent-loss count the
        trace_spans_dropped_total{reason="ring_wrap"} series exposes."""
        return max(0, self.recorded - self.capacity)

    @property
    def sampling_rejected(self) -> int:
        """record() calls dropped because their context was unsampled
        (head-sampling). Expected under a <1.0 sample rate — the counter
        makes the loss *visible*, it does not make it wrong."""
        return self._rejected.__reduce__()[1][0]

    # -- sampling -----------------------------------------------------------

    def sample(self, trace_id: Optional[str] = None) -> bool:
        """Head sampling decision for a new root. Deterministic in the trace
        id so retries of the same trace (and every server seeing it) agree;
        rate 0.0 samples nothing, 1.0 samples everything."""
        if self.sample_rate <= 0.0:
            return False
        if self.sample_rate >= 1.0:
            return True
        if trace_id is None:
            trace_id = "00000001"
        return int(trace_id[:8], 16) < self.sample_rate * float(1 << 32)

    def root_from_headers(self, headers) -> SpanContext:
        """Adopt the remote context from ``traceparent`` (its sampled flag is
        authoritative — head-based sampling), else start a fresh root sampled
        by this collector's rate.

        Exception: rate 0.0 is the operator's kill switch — it wins even over
        a sampled remote flag, so an untrusted client header can never force
        recording back on (the trace id is still adopted for correlation)."""
        remote = SpanContext.from_headers(headers)
        if remote is not None:
            if self.sample_rate <= 0.0 and remote.sampled:
                from dataclasses import replace

                return replace(remote, sampled=False)
            return remote
        from production_stack_tpu.tracing.context import gen_span_id, gen_trace_id

        tid = gen_trace_id()
        return SpanContext(
            trace_id=tid, span_id=gen_span_id(), sampled=self.sample(tid)
        )

    # -- recording ----------------------------------------------------------

    def record(
        self,
        name: str,
        ctx: Optional[SpanContext],
        start: float,
        duration: float,
        **attrs,
    ) -> None:
        """Store one completed span. No-op for missing/unsampled contexts —
        this is the entire overhead of tracing when sampling is off (plus one
        atomic counter bump for unsampled contexts, so trace loss is
        observable)."""
        if ctx is None:
            return
        if not ctx.sampled:
            next(self._rejected)
            return
        span = Span(
            trace_id=ctx.trace_id,
            span_id=ctx.span_id,
            parent_id=ctx.parent_id,
            name=name,
            start=start,
            duration=max(0.0, duration),
            attrs=attrs,
        )
        # lock-free-ish: the counter hands each writer a distinct slot; a
        # reader may see a slot mid-overwrite as either old or new span —
        # both are valid spans, so snapshots never tear
        self._slots[next(self._cursor) % self.capacity] = span

    # -- reading ------------------------------------------------------------

    def spans(self) -> list[Span]:
        return [s for s in list(self._slots) if s is not None]

    def traces(
        self, trace_id: Optional[str] = None, limit: int = 50
    ) -> list[dict]:
        """Spans grouped per trace, most recently started trace first."""
        by_trace: dict[str, list[Span]] = {}
        for s in self.spans():
            by_trace.setdefault(s.trace_id, []).append(s)
        if trace_id is not None:
            by_trace = {
                t: ss for t, ss in by_trace.items() if t == trace_id
            }
        ordered = sorted(
            by_trace.items(),
            key=lambda kv: max(s.start for s in kv[1]),
            reverse=True,
        )[: max(0, int(limit))]
        return [
            {
                "trace_id": t,
                "spans": [s.to_dict() for s in sorted(ss, key=lambda s: s.start)],
            }
            for t, ss in ordered
        ]

    def export(self, trace_id: Optional[str] = None, limit: int = 50) -> dict:
        """JSON-serializable payload for /v1/traces and offline analysis
        (scripts/trace_report.py consumes exactly this shape)."""
        return {
            "sample_rate": self.sample_rate,
            "capacity": self.capacity,
            "recorded_total": self.recorded,
            "exported_at": time.time(),
            "traces": self.traces(trace_id=trace_id, limit=limit),
        }

    def export_json(self, **kw) -> str:
        return json.dumps(self.export(**kw))

    def reset(self) -> None:
        """Debug/bench only: clear the buffer so a phase's traces describe
        that phase."""
        self._slots = [None] * self.capacity
        self._cursor = itertools.count()
        self._rejected = itertools.count()


# -- process-global collector -------------------------------------------------

_collector = SpanCollector()
_lock = threading.Lock()


def configure_tracing(
    sample_rate: Optional[float] = None, capacity: Optional[int] = None
) -> SpanCollector:
    """(Re)configure the process-global collector. Resizing replaces the
    buffer (old spans drop); a pure rate change keeps recorded spans."""
    global _collector
    with _lock:
        if capacity is not None and int(capacity) != _collector.capacity:
            _collector = SpanCollector(
                capacity=capacity,
                sample_rate=(
                    _collector.sample_rate if sample_rate is None else sample_rate
                ),
            )
        elif sample_rate is not None:
            _collector.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        return _collector


def get_collector() -> SpanCollector:
    return _collector


def render_collector_metrics(labels: str) -> list[str]:
    """Prometheus lines for span-loss visibility (rendered by every server
    hosting the collector — engine, router, fake engine): the ring wrapping
    and head-sampling both drop spans BY DESIGN, and an attribution built on
    an incomplete trace is misleading unless the loss is measurable."""
    col = get_collector()
    return [
        "# TYPE vllm:trace_spans_recorded_total counter",
        f"vllm:trace_spans_recorded_total{{{labels}}} {col.recorded}",
        "# TYPE vllm:trace_spans_dropped_total counter",
        f'vllm:trace_spans_dropped_total{{{labels},reason="ring_wrap"}} '
        f"{col.overwritten}",
        f'vllm:trace_spans_dropped_total{{{labels},reason="unsampled"}} '
        f"{col.sampling_rejected}",
        "# TYPE vllm:trace_buffer_capacity gauge",
        f"vllm:trace_buffer_capacity{{{labels}}} {col.capacity}",
    ]


def export_for_query(query) -> "tuple[dict, int]":
    """Shared ``GET /v1/traces`` implementation for every server hosting the
    collector (router, engine, fake engine): parse ``?trace_id=``/``?limit=``
    from an HTTP query mapping and return ``(json_payload, status)`` — one
    place, so the export contract cannot drift between surfaces."""
    try:
        limit = int(query.get("limit", "50"))
    except (TypeError, ValueError):
        return {"error": "limit must be an int"}, 400
    return (
        get_collector().export(trace_id=query.get("trace_id"), limit=limit),
        200,
    )


# -- ambient context (KV-offload spans) ---------------------------------------
#
# The offload tiers run deep inside the scheduler's admission path, far from
# any HTTP handler; the admitting sequence's context is published here (engine
# device thread) so spill/restore spans parent under the request that caused
# them.

_current: contextvars.ContextVar = contextvars.ContextVar(
    "pstpu_trace_ctx", default=None
)


def set_current(ctx: Optional[SpanContext]):
    return _current.set(ctx)


def reset_current(token) -> None:
    _current.reset(token)


def current_context() -> Optional[SpanContext]:
    return _current.get()
