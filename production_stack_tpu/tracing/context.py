"""W3C trace-context propagation for request-level distributed tracing.

One request entering the stack carries a single 128-bit trace id from the
router proxy through the engine API server down to the KV-offload tiers; every
hop records spans under that id, so a trace stitches the whole
router -> engine -> offload path back together for latency attribution.

The wire format is the W3C ``traceparent`` header
(https://www.w3.org/TR/trace-context/):

    traceparent: 00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>

Only version ``00`` and the ``sampled`` flag bit (0x01) are interpreted;
unknown versions and malformed headers are ignored (a bad client header must
never break proxying). ``tracestate`` is not used.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, replace
from typing import Optional

TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})"
    r"-(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)


def gen_trace_id() -> str:
    return os.urandom(16).hex()


def gen_span_id() -> str:
    return os.urandom(8).hex()


@dataclass(frozen=True)
class SpanContext:
    """Identity of one span: which trace it belongs to, its own id, the id of
    its parent span (None for a root), and whether the trace is sampled.

    The sampled flag is decided ONCE at the root (head-based sampling) and
    propagated, so a trace is either recorded end-to-end or not at all —
    partial traces are useless for attribution.
    """

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    sampled: bool = True

    def child(self) -> "SpanContext":
        """Context for a new span parented under this one."""
        return replace(self, span_id=gen_span_id(), parent_id=self.span_id)

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    @staticmethod
    def parse(header: Optional[str]) -> "Optional[SpanContext]":
        """Parse a ``traceparent`` header; None on anything malformed.

        An all-zero trace or span id is invalid per the spec (it would
        collide every such request into one phantom trace)."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None or m.group("version") == "ff":
            return None
        trace_id, span_id = m.group("trace_id"), m.group("span_id")
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return SpanContext(
            trace_id=trace_id,
            span_id=span_id,
            parent_id=None,
            sampled=bool(int(m.group("flags"), 16) & 0x01),
        )

    @staticmethod
    def from_headers(headers) -> "Optional[SpanContext]":
        """Extract the remote context from an HTTP header mapping."""
        try:
            return SpanContext.parse(headers.get(TRACEPARENT_HEADER))
        except Exception:  # noqa: BLE001 - malformed headers never break serving
            return None

    @staticmethod
    def new_root(sampled: bool = True) -> "SpanContext":
        return SpanContext(
            trace_id=gen_trace_id(), span_id=gen_span_id(), sampled=sampled
        )
