"""Per-phase Prometheus histograms backing the tracing subsystem.

Four request-phase distributions, named to mirror vLLM's metric definitions so
the reference dashboard's phase-breakdown queries work unchanged against our
``/metrics`` (the same contract utils/metrics.py keeps for TTFT/e2e):

- ``vllm:request_queue_time_seconds``   — scheduler admit -> first dispatch
- ``vllm:request_prefill_time_seconds`` — first dispatch -> first token
- ``vllm:time_per_output_token_seconds``— decode time / output tokens (TPOT)
- ``vllm:kv_offload_restore_seconds``   — offload-tier restore batches (no
  vLLM equivalent; kept in the ``vllm:`` namespace so one scrape job covers
  the engine surface)

These are observed by the ENGINE (it owns the phases) and always-on — a few
histogram observes per request are noise next to a device step — while span
recording is gated by the sampling knob. The router's ``/metrics`` renders
them too (zero-count in a router-only process) so dashboards can point either
scrape job at the same panel set.
"""

from __future__ import annotations

from production_stack_tpu.utils.metrics import LATENCY_BUCKETS, Histogram

# vLLM's time_per_output_token histogram boundaries (seconds)
TPOT_BUCKETS = (
    0.01, 0.025, 0.05, 0.075, 0.1, 0.15, 0.2, 0.3, 0.4, 0.5, 0.75, 1.0, 2.5,
)
# restore batches are bounded by kv_offload_max_io_pages; sub-second to a few
# seconds on network-attached hosts
RESTORE_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

queue_time_hist = Histogram(
    "vllm:request_queue_time_seconds", LATENCY_BUCKETS,
    "Request queue wait (arrival to first prefill dispatch)",
)
prefill_time_hist = Histogram(
    "vllm:request_prefill_time_seconds", LATENCY_BUCKETS,
    "Prefill phase duration (first dispatch to first token)",
)
decode_step_time_hist = Histogram(
    "vllm:time_per_output_token_seconds", TPOT_BUCKETS,
    "Mean decode time per output token (first token to finish)",
)
offload_restore_hist = Histogram(
    "vllm:kv_offload_restore_seconds", RESTORE_BUCKETS,
    "KV offload-tier restore batch duration",
)
# dispatch-granular long-context prefill observability (ISSUE 6): per-chunk
# device wall time, and decode step time per token WHILE a prefill is
# resident — the pair the Grafana prefill-phase panel charts to show a 32k
# prompt streaming through without starving co-scheduled decodes
prefill_chunk_hist = Histogram(
    "vllm:prefill_chunk_seconds", TPOT_BUCKETS + (5.0, 10.0),
    "One chunked-prefill dispatch's device wall time",
)
interleaved_decode_hist = Histogram(
    "vllm:interleaved_decode_step_seconds", TPOT_BUCKETS,
    "Decode time per output token for bursts interleaved with an "
    "in-flight prefill",
)

PHASE_HISTOGRAMS = (
    queue_time_hist,
    prefill_time_hist,
    decode_step_time_hist,
    offload_restore_hist,
    prefill_chunk_hist,
    interleaved_decode_hist,
)


def render_phase_histograms(labels: str) -> list[str]:
    """Exposition lines for all four phase histograms under ``labels``."""
    lines: list[str] = []
    for h in PHASE_HISTOGRAMS:
        lines.extend(h.render(labels))
    return lines


def reset_phase_histograms() -> None:
    """Debug/bench only (the /metrics/reset endpoints)."""
    for h in PHASE_HISTOGRAMS:
        h.reset()
